"""Wedge-proof driver bench (round-3 verdict item 2): the probe loop must
survive a hung tunnel that recovers mid-budget, give up fast on devices
that will never appear, and merge CPU-fallback results without clobbering
real device numbers."""

import time

import bench


def _mk_probe(script):
    """probe_fn returning scripted results; records call count."""
    calls = {"n": 0}

    def probe(force, timeout):
        i = min(calls["n"], len(script) - 1)
        calls["n"] += 1
        return script[i]

    probe.calls = calls
    return probe


def test_probe_loop_hang_then_recover():
    """Round 3's failure: one hung probe cost the whole TPU artifact.  Two
    simulated wedges followed by a recovery must yield the device."""
    probe = _mk_probe([
        (None, "backend init hung (> 90s)"),
        (None, "backend init hung (> 90s)"),
        ("tpu", None),
    ])
    fired = []
    backend, err = bench._probe_loop(
        None, time.monotonic() + 300, probe_timeout=1,
        probe_fn=probe, sleep_s=0.01,
        on_first_failure=lambda: fired.append(1),
    )
    assert backend == "tpu" and err is None
    assert probe.calls["n"] == 3
    assert fired == [1]  # fallback starter fires once, on the FIRST failure


def test_probe_loop_plain_cpu_returns_immediately():
    """A healthy jax with no accelerator is not a wedge — re-probing cannot
    conjure a device, so the loop must hand over to the fallback at once."""
    probe = _mk_probe([("cpu", None)])
    t0 = time.monotonic()
    backend, err = bench._probe_loop(
        None, time.monotonic() + 300, probe_timeout=1,
        probe_fn=probe, sleep_s=5.0,
    )
    assert backend is None and "no accelerator" in err
    assert probe.calls["n"] == 1
    assert time.monotonic() - t0 < 1.0  # no sleep taken


def test_probe_loop_exhausts_budget_and_reports_last_error():
    probe = _mk_probe([(None, "wedged")])
    backend, err = bench._probe_loop(
        None, time.monotonic() + 0.2, probe_timeout=0.05,
        probe_fn=probe, sleep_s=0.01, reserve_s=0.05,
    )
    assert backend is None and err == "wedged"
    assert probe.calls["n"] >= 1


def test_merge_fallback_fills_only_missing_or_failed():
    configs = {
        "hash": {"value": 30.0},          # real device number: keep
        "cdc": {"error": "boom"},          # device leg failed: fill
    }                                      # merkle_diff never ran: fill
    fallback = {
        "hash": {"value": 0.03},
        "cdc": {"value": 0.5},
        "merkle_diff": {"value": 83000.0},
        "broken": {"error": "child failed"},  # child errors never merge
    }
    filled = bench._merge_fallback(configs, fallback)
    assert sorted(filled) == ["cdc", "merkle_diff"]
    assert configs["hash"] == {"value": 30.0}
    assert configs["cdc"]["value"] == 0.5
    assert configs["cdc"]["backend"] == "cpu-fallback"
    assert configs["merkle_diff"]["backend"] == "cpu-fallback"
    assert "broken" not in configs
