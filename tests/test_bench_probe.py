"""Wedge-proof driver bench (round-3 verdict item 2): the probe loop must
survive a hung tunnel that recovers mid-budget, give up fast on devices
that will never appear, and merge CPU-fallback results without clobbering
real device numbers."""

import time

import bench


def _mk_probe(script):
    """probe_fn returning scripted results; records call count."""
    calls = {"n": 0}

    def probe(force, timeout):
        i = min(calls["n"], len(script) - 1)
        calls["n"] += 1
        return script[i]

    probe.calls = calls
    return probe


def test_probe_loop_hang_then_recover():
    """Round 3's failure: one hung probe cost the whole TPU artifact.  Two
    simulated wedges followed by a recovery must yield the device."""
    probe = _mk_probe([
        (None, "backend init hung (> 90s)"),
        (None, "backend init hung (> 90s)"),
        ("tpu", None),
    ])
    fired = []
    backend, err = bench._probe_loop(
        None, time.monotonic() + 300, probe_timeout=1,
        probe_fn=probe, sleep_s=0.01,
        on_first_failure=lambda: fired.append(1),
    )
    assert backend == "tpu" and err is None
    assert probe.calls["n"] == 3
    assert fired == [1]  # fallback starter fires once, on the FIRST failure


def test_probe_loop_plain_cpu_returns_immediately():
    """A healthy jax with no accelerator is not a wedge — re-probing cannot
    conjure a device, so the loop must hand over to the fallback at once."""
    probe = _mk_probe([("cpu", None)])
    t0 = time.monotonic()
    backend, err = bench._probe_loop(
        None, time.monotonic() + 300, probe_timeout=1,
        probe_fn=probe, sleep_s=5.0,
    )
    assert backend is None and "no accelerator" in err
    assert probe.calls["n"] == 1
    assert time.monotonic() - t0 < 1.0  # no sleep taken


def test_probe_loop_exhausts_budget_and_reports_last_error():
    probe = _mk_probe([(None, "wedged")])
    backend, err = bench._probe_loop(
        None, time.monotonic() + 0.2, probe_timeout=0.05,
        probe_fn=probe, sleep_s=0.01, reserve_s=0.05,
    )
    assert backend is None and err == "wedged"
    assert probe.calls["n"] >= 1


def test_merge_fallback_fills_only_missing_or_failed():
    configs = {
        "hash": {"value": 30.0},          # real device number: keep
        "cdc": {"error": "boom"},          # device leg failed: fill
    }                                      # merkle_diff never ran: fill
    fallback = {
        "hash": {"value": 0.03},
        "cdc": {"value": 0.5},
        "merkle_diff": {"value": 83000.0},
        "broken": {"error": "child failed"},  # child errors never merge
    }
    filled = bench._merge_fallback(configs, fallback)
    assert sorted(filled) == ["cdc", "merkle_diff"]
    assert configs["hash"] == {"value": 30.0}
    assert configs["cdc"]["value"] == 0.5
    assert configs["cdc"]["backend"] == "cpu-fallback"
    assert configs["merkle_diff"]["backend"] == "cpu-fallback"
    assert "broken" not in configs


# ---------------------------------------------------------------------------
# _timed_reps_pipelined: the round-4 perf re-pricing (1.7x on hash) rides
# on this helper fencing every rep exactly once, in order, with bounded
# in-flight depth (round-4 verdict item 8: trusted, never tested)
# ---------------------------------------------------------------------------


class _Tracker:
    """Scripted dispatch/fence pair recording order and in-flight depth."""

    def __init__(self):
        self.next_id = 0
        self.outstanding = []       # dispatched, not yet fenced
        self.fenced = []            # fence order
        self.dispatch_order = []
        self.high_water = 0

    def dispatch(self):
        tok = self.next_id
        self.next_id += 1
        self.outstanding.append(tok)
        self.dispatch_order.append(tok)
        self.high_water = max(self.high_water, len(self.outstanding))
        return tok

    def fence(self, tok):
        assert tok in self.outstanding, f"fenced {tok} twice or never dispatched"
        self.outstanding.remove(tok)
        self.fenced.append(tok)


def test_pipelined_fences_every_rep_once_in_order():
    for reps in (1, 2, 3, 7):
        tr = _Tracker()
        dts = bench._timed_reps_pipelined(tr.dispatch, tr.fence, reps, depth=2)
        assert len(dts) == reps
        # every dispatch fenced exactly once, nothing left in flight
        assert tr.outstanding == []
        assert sorted(tr.fenced) == tr.dispatch_order[: len(tr.fenced)]
        # fences happen in dispatch order (no reorder, no drop)
        assert tr.fenced == sorted(tr.fenced)
        # primer + reps dispatches total
        assert tr.next_id == reps + 1


def test_pipelined_depth_bounds_inflight():
    for depth in (1, 2, 3):
        tr = _Tracker()
        bench._timed_reps_pipelined(tr.dispatch, tr.fence, 8, depth=depth)
        # primer counts toward in-flight until its fence; after it the
        # window holds at most `depth` unfenced reps
        assert tr.high_water <= depth + 1
        assert tr.fenced == list(range(9))


def test_pipelined_depth1_degrades_to_serial_alternation():
    events = []

    def dispatch():
        events.append("d")
        return len(events)

    def fence(tok):
        events.append("f")

    bench._timed_reps_pipelined(dispatch, fence, 4, depth=1)
    # primer d, first rep d, primer f, then strict f/d alternation with
    # never more than one rep awaiting its fence
    pend = 0
    for e in events:
        pend += 1 if e == "d" else -1
        assert 0 <= pend <= 2
    assert pend == 0


def test_serial_fence_env_restores_strict_alternation(monkeypatch):
    monkeypatch.setenv("BENCH_SERIAL_FENCE", "1")
    events = []

    def dispatch():
        events.append("d")
        return len(events)

    def fence(tok):
        events.append("f")

    dts = bench._timed_reps_pipelined(dispatch, fence, 3)
    assert len(dts) == 3
    assert events == ["d", "f"] * 3  # no primer, no overlap


def test_resume_probe_measures_fault_to_redelivery(monkeypatch):
    """Config 6 (resume latency) must produce a real, positive
    fault->first-redelivered-frame number on a tiny session and survive
    being run host-only (no JAX involvement)."""
    monkeypatch.setenv("BENCH_RESUME_ROWS", "200")
    monkeypatch.setenv("BENCH_RESUME_REPS", "3")
    res = bench.bench_resume(quick=True, backend="host")
    assert res["metric"] == "resume_latency" and res["unit"] == "ms"
    assert res["value"] > 0 and res["p90_ms"] >= res["value"]
    assert res["rows"] == 200 and res["wire_bytes"] > 0


def test_resume_probe_registered_in_host_group():
    # config 6 needs no device: it must be in BENCHES and NOT in the
    # device leg (a wedged tunnel cannot cost the recovery number)
    assert bench.BENCHES["6"][0] == "resume"


def test_peak_span_guards_drain_and_post_stall():
    # queue-drain span (0.05 << half median) excluded; the 0.9 span right
    # after the 2.0 stall is drain-compressed (advisor r4) - excluded too
    dts = [1.0, 1.0, 2.0, 0.9, 0.05, 0.95]
    assert bench._peak_span(dts) == 0.95
    # no credible spans at all -> fall back to the median
    assert bench._peak_span([1.0]) == 1.0


# ---------------------------------------------------------------------------
# --metrics: per-config registry snapshots ride the artifact (ISSUE 3)
# ---------------------------------------------------------------------------


def test_attach_metrics_noop_without_flag():
    res = {"value": 1.0}
    bench._METRICS["on"] = False
    bench._attach_metrics(res)
    assert "metrics" not in res


def test_metrics_snapshot_rides_config_result_and_resets(monkeypatch):
    """bench --metrics: each config's result carries the registry
    snapshot for ITS run (attribution), the registry resetting between
    configs; the snapshot itself must be JSON-able and show the
    config's actual session traffic."""
    import json

    from dat_replication_protocol_tpu.obs import metrics as obs_metrics

    monkeypatch.setenv("BENCH_RESUME_ROWS", "200")
    monkeypatch.setenv("BENCH_RESUME_REPS", "2")
    was_on = obs_metrics.OBS.on
    obs_metrics.REGISTRY.reset()
    try:
        bench._metrics_on()
        res = bench.bench_resume(quick=True, backend="host")
        bench._attach_metrics(res)
    finally:
        bench._METRICS["on"] = False
        obs_metrics.OBS.on = was_on
        obs_metrics.REGISTRY.reset()
    snap = json.loads(json.dumps(res["metrics"]))  # parseable as-is
    # the resume probe's story is in the numbers: attempts, faults, and
    # replayed journal bytes all nonzero, decoder traffic attributed
    assert snap["counters"]["reconnect.attempts"] > 0
    assert snap["counters"]["reconnect.faults"] > 0
    assert snap["counters"]["decoder.changes"] > 0
    assert snap["histograms"]["decoder.dispatch.seconds"]["count"] > 0
    # and the attach RESET the registry for the next config
    assert obs_metrics.REGISTRY.counter("reconnect.attempts").value == 0


def test_cpu_fallback_child_inherits_metrics_flag(monkeypatch):
    """The fallback child's numbers need attribution too: when the
    parent runs --metrics, the spawned argv must carry it."""
    captured = {}

    class FakeProc:
        pass

    def fake_popen(argv, **kwargs):
        captured["argv"] = argv
        return FakeProc()

    import subprocess

    monkeypatch.setattr(subprocess, "Popen", fake_popen)
    bench._METRICS["on"] = True
    try:
        bench._start_cpu_fallback(["3"], quick=True, budget_s=60)
    finally:
        bench._METRICS["on"] = False
    assert "--metrics" in captured["argv"]


# ---------------------------------------------------------------------------
# config 12 (ISSUE 12): the snapshot bootstrap's acceptance criteria run
# LIVE at reduced size — the tier-1 budget-gated face of the bench
# ---------------------------------------------------------------------------


def test_snapshot_bootstrap_live_gate(monkeypatch):
    """Bytes-on-wire scale with staleness (2% stale => <= 5% of the
    cold transfer), a cold flash crowd of 8 leaves source digest work
    constant (hash_ratio 1.0 — ZERO marginal hash bytes), and the
    chaos arm's torn-wire resume is exactly-once."""
    monkeypatch.setenv("BENCH_SNAPSHOT_MIB", "4")
    monkeypatch.setenv("BENCH_SNAPSHOT_JOINERS", "8")
    res = bench.bench_snapshot_bootstrap(quick=True, backend="host")
    assert res["metric"] == "snapshot_bootstrap_stale_wire_ratio"
    assert res["value"] <= 0.05, res  # staleness, not dataset size
    assert res["crowd_hash_bytes"] == 0  # hash once, serve 8
    assert res["hash_ratio"] == 1.0
    assert res["chaos"]["resumed"] is True
    assert res["chaos"]["exactly_once"] is True
    assert res["chunks_reused"] > 0 and res["symbols"] > 0


def test_snapshot_bootstrap_registered_in_host_group():
    # config 12 needs no device: it must be in BENCHES and NOT in the
    # device leg (the TPU watch script drives the device side)
    assert bench.BENCHES["12"][0] == "snapshot_bootstrap"


# ---------------------------------------------------------------------------
# config 13 (ISSUE 14): the wire pump A/B's acceptance criteria run
# LIVE at reduced size — the tier-1 budget-gated face of the bench
# ---------------------------------------------------------------------------


def test_wire_pump_live_gate(monkeypatch):
    """Both pump routes complete the e2e digest session byte-for-byte
    (the A/B is only meaningful if both sides finish), the native
    route reports its probe, and the hub arm's aggregate exists for
    every requested session count."""
    monkeypatch.setenv("BENCH_PUMP_MIB", "8")
    monkeypatch.setenv("BENCH_PUMP_SESSIONS", "1,2")
    monkeypatch.setenv("BENCH_PUMP_REPS", "1")
    res = bench.bench_wire_pump(quick=True, backend="host")
    assert res["metric"] == "wire_pump_e2e_throughput"
    assert res["value"] > 0 and res["python_pump_gib_s"] > 0
    assert res["e2e_host_gib_s"] == res["value"]
    assert set(res["hub_agg_gib_s"]) == {"1", "2"}
    assert all(v > 0 for v in res["hub_agg_gib_s"].values())
    assert res["probe"]["route"] in ("native", "python")
    assert res["reduced_config"] is True


def test_wire_pump_registered_in_host_group():
    # config 13 needs no device: it must be in BENCHES and NOT in the
    # device leg (the TPU watch script drives the device side)
    assert bench.BENCHES["13"][0] == "wire_pump"
