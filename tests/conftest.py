"""Test env: force a virtual 8-device CPU mesh before JAX initializes.

Multi-chip hardware is unavailable in CI; sharding tests run against
``--xla_force_host_platform_device_count=8`` on the CPU backend, which
exercises the same mesh/collective code paths XLA uses on real ICI.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
