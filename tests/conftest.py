"""Test env: force a virtual 8-device CPU mesh before JAX initializes.

Multi-chip hardware is unavailable in CI; sharding tests run against
``--xla_force_host_platform_device_count=8`` on the CPU backend, which
exercises the same mesh/collective code paths XLA uses on real ICI.

This must *override* (not just default) JAX_PLATFORMS: the dev image sets
``JAX_PLATFORMS=axon`` (one tunneled TPU chip), which cannot host the
8-way mesh tests and pays a real-hardware compile per parametrized case.
Set ``DAT_TPU_TESTS=1`` to opt back into running the suite on the real
chip (single-device tests only).
"""

import os

if not os.environ.get("DAT_TPU_TESTS"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    # the dev image's sitecustomize re-forces JAX_PLATFORMS=axon after the
    # environment is read; jax.config wins over both
    import jax

    jax.config.update("jax_platforms", "cpu")

    # persistent compile cache: the CPU backend's scanned-BLAKE2b/tree
    # programs take minutes to compile cold; cached, suite reruns drop
    # from ~15 min to ~4 min (measured)
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))
    from dat_replication_protocol_tpu.utils.cache import enable_compile_cache

    enable_compile_cache("tests", env_var="DAT_TEST_COMPILE_CACHE")


# -- shared telemetry isolation ---------------------------------------------

import pytest  # noqa: E402


@pytest.fixture
def obs_enabled():
    """Enable the obs gate for one test with clean metric values, an
    empty event ring, an empty span ring, a disarmed flight recorder,
    and a reset device sentinel, restoring the prior gate state
    afterwards — all five are process-global, so isolation is
    explicit."""
    from dat_replication_protocol_tpu.obs import device, events, flight, \
        metrics, propagation, tracing, watermarks, wirecost

    was_on = metrics.OBS.on
    metrics.REGISTRY.reset()
    events.EVENTS.clear()
    tracing.SPANS.clear()
    flight.FLIGHT._reset_for_tests()
    device.SENTINEL.reset_for_tests()
    device.reset_engine_notes()
    watermarks.WATERMARKS.reset_for_tests()
    propagation.PROPAGATION.reset_for_tests()
    wirecost.WIRECOST.reset_for_tests()
    metrics.enable()
    try:
        yield metrics
    finally:
        metrics.OBS.on = was_on
        metrics.REGISTRY.reset()
        events.EVENTS.clear()
        events.EVENTS.detach_sink()
        tracing.SPANS.clear()
        tracing.SPANS.detach_sink()
        flight.FLIGHT._reset_for_tests()
        device.SENTINEL.reset_for_tests()
        device.reset_engine_notes()
        watermarks.WATERMARKS.reset_for_tests()
        propagation.PROPAGATION.reset_for_tests()
        wirecost.WIRECOST.reset_for_tests()
