"""Single-pass content addressing (ISSUE 7): route equivalence, the
on-chip cross-check, donated/pipelined transfers, and the ptr-array
native hash entry.

The core contract: EVERY content-addressing route — the fused native
single pass (``fused1p``), the two-pass native composition, the device
single-residency pipeline, the pallas extraction kernels (interpret
mode), and a plain hashlib reference — produces byte-identical cuts and
digests for the same stream.
"""

from __future__ import annotations

import hashlib
import os
import random

import numpy as np
import pytest

from dat_replication_protocol_tpu.ops import rabin
from dat_replication_protocol_tpu.runtime import native
from dat_replication_protocol_tpu.runtime.content import (
    content_digests,
    resolve_cdc_route,
)


def _ref_digests(buf: np.ndarray, cuts) -> list[bytes]:
    offs = [0] + list(cuts[:-1])
    return [
        hashlib.blake2b(buf[a:b].tobytes(), digest_size=32).digest()
        for a, b in zip(offs, cuts)
    ]


# -- route equivalence fuzz ---------------------------------------------------


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_fused1p_matches_two_pass_and_hashlib(seed, monkeypatch):
    """Random sizes/parameters: fused1p cuts+digests == two-pass ==
    hashlib, including chunk-boundary edge shapes (sizes straddling
    min/max chunk, block multiples, single-byte tails)."""
    monkeypatch.delenv("DAT_CDC_ROUTE", raising=False)
    rng = random.Random(seed)
    sizes = [
        rng.randrange(0, 200_000),
        rng.choice([1, 127, 128, 129, 4096]),          # block edges
        rng.choice([1 << 11, (1 << 15) + 1, 65_537]),  # min/max chunk edges
    ]
    for n in sizes:
        buf = np.frombuffer(rng.randbytes(n), dtype=np.uint8)
        avg = rng.choice([8, 10, 13])
        mn = 1 << (avg - 2)
        mx = 1 << (avg + 2)
        cuts_f, digs_f = content_digests(buf, avg, mn, mx, route="fused1p")
        cuts_2, digs_2 = content_digests(buf, avg, mn, mx, route="2p")
        assert cuts_f == cuts_2, (n, avg)
        assert np.array_equal(digs_f, digs_2), (n, avg)
        ref = _ref_digests(buf, cuts_f)
        assert [digs_f[i].tobytes() for i in range(len(ref))] == ref
        if n:
            assert cuts_f[-1] == n
            assert cuts_f == rabin.chunk_stream(buf, avg, mn, mx)


def test_edge_cases_empty_single_byte_and_forced_cuts():
    # empty blob
    cuts, digs = content_digests(b"")
    assert cuts == [] and digs.shape == (0, 32)
    # single byte
    cuts, digs = content_digests(b"x")
    assert cuts == [1]
    assert digs[0].tobytes() == hashlib.blake2b(
        b"x", digest_size=32).digest()
    # all-zero data has NO gear candidates: every cut is a forced
    # max_size cut, plus the sub-min tail
    z = np.zeros(100_000, dtype=np.uint8)
    cuts_f, digs_f = content_digests(z, 10, 256, 4096, route="fused1p")
    cuts_2, digs_2 = content_digests(z, 10, 256, 4096, route="2p")
    assert cuts_f == cuts_2
    assert np.array_equal(digs_f, digs_2)
    sizes = np.diff([0] + cuts_f)
    assert (sizes[:-1] == 4096).all()
    # min_size below the fused kernel's thinning range: transparently
    # served by the two-pass route, still identical
    b = np.frombuffer(random.Random(7).randbytes(5000), dtype=np.uint8)
    cuts_s, digs_s = content_digests(b, 6, 16, 256)
    cuts_s2, digs_s2 = content_digests(b, 6, 16, 256, route="2p")
    assert cuts_s == cuts_s2 and np.array_equal(digs_s, digs_s2)


def test_native_cdc_hash_parity_direct():
    """The C entry against the composed native two-pass, incl. the
    multi-slab path (the engine's slabs are 32 MiB: this buffer forces
    the cross-slab greedy frontier, candidate-queue erase, seam-window
    dedup, and the anti-phase job split to all run) and an explicit
    multi-thread split."""
    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(3)
    buf = rng.integers(0, 256, (70 << 20) + 321, dtype=np.uint8)
    out = native.cdc_hash(buf, 13, 10, 1 << 11, 1 << 15)
    assert out is not None
    cuts, digs = out
    cands = native.gear_candidates(buf, 13, 10)
    ref_cuts = rabin._greedy_select(cands, len(buf), 1 << 11, 1 << 15)
    assert cuts.tolist() == ref_cuts
    ends = np.asarray(ref_cuts, np.int64)
    offs = np.concatenate([np.zeros(1, np.int64), ends[:-1]])
    ref = native.hash_many(buf, offs, ends - offs)
    assert np.array_equal(digs, ref)
    # out-of-range thinning refuses (caller falls back)
    assert native.cdc_hash(buf, 13, 4, 8, 64) is None


def test_route_resolution_and_invalid_values(monkeypatch):
    monkeypatch.delenv("DAT_CDC_ROUTE", raising=False)
    monkeypatch.delenv("DAT_CDC_FIRST_KERNEL", raising=False)
    assert resolve_cdc_route() == "fused1p"
    monkeypatch.setenv("DAT_CDC_ROUTE", "bitmask")
    assert resolve_cdc_route() == "2p"
    # invalid values resolve to the DEFAULTS, never a crash or a lie
    monkeypatch.setenv("DAT_CDC_ROUTE", "Fused1P")
    assert resolve_cdc_route() == "fused1p"
    assert rabin.effective_route(use_pallas=False) == "bitmask"
    monkeypatch.setenv("DAT_CDC_ROUTE", "fused1p")
    assert rabin.effective_route(use_pallas=True) == "fused1p"
    # off-pallas the fused1p extraction aliases to bitmask
    assert rabin.effective_route(use_pallas=False) == "bitmask"
    # and the extraction path still yields the host-reference candidates
    data = random.Random(13).randbytes(6 * 4096 + 321)
    buf = np.frombuffer(data, dtype=np.uint8)
    ref = rabin.host_thin(rabin.host_candidates(data, 8), 8)
    got = rabin._device_candidates(buf, 8, 1 << 12, 4, thin_bits=8)
    assert got.tolist() == ref


# -- the fused1p pallas extraction + on-chip cross-check ----------------------


def test_checked_kernel_matches_fused_kernel_interpret():
    import jax.numpy as jnp

    from dat_replication_protocol_tpu.ops.fused_cdc_hash_pallas import (
        gear_window_first_checked,
    )
    from dat_replication_protocol_tpu.ops.rabin_pallas import (
        gear_window_first_pallas,
    )

    T, stride, thin = 2, 2048, 9
    data = random.Random(17).randbytes(T * stride)
    words = jnp.asarray(np.frombuffer(data, dtype=np.uint8).view("<u4"))
    rows = rabin._build_rows(
        words, jnp.zeros((rabin._PREFIX_WORDS,), jnp.uint32), T, stride
    )
    ref = np.asarray(gear_window_first_pallas(rows, 8, thin, interpret=True))
    got, viol = gear_window_first_checked(rows, 8, thin, interpret=True)
    assert np.array_equal(ref, np.asarray(got))
    assert int(viol) == 0
    assert (np.asarray(got) < (1 << 30)).any(), "weak fixture: no candidates"


def test_crosscheck_refusal_falls_back_to_bitmask(monkeypatch, obs_enabled):
    """A divergent checked-kernel output (viol != 0) must be REFUSED:
    collect() recomputes on the bitmask route and the refusal counter
    fires — the cuts that come back are still the host-reference ones."""
    import jax.numpy as jnp

    from dat_replication_protocol_tpu.obs import metrics as obs_metrics
    from dat_replication_protocol_tpu.ops import fused_cdc_hash_pallas as fch
    from dat_replication_protocol_tpu.ops import rabin_pallas

    # force the pallas routing decision on a CPU host, with both pallas
    # kernels redirected to their portable-XLA equivalents
    monkeypatch.setattr(rabin, "pallas_active", lambda: True)
    monkeypatch.setattr(
        rabin_pallas, "gear_candidates_pallas",
        lambda rows, avg_bits, **kw: rabin.gear_candidates_tiled(
            rows, avg_bits),
    )

    def fake_checked(rows, avg_bits, thin_bits, **kw):
        # the CORRECT window-first reduction, but claiming divergence
        vw = rabin.gear_candidates_tiled(rows, avg_bits)[
            :, rabin._PREFIX // rabin.PACK:]
        wpw = (1 << thin_bits) // rabin.PACK
        first = rabin._first_bit_per_window(vw.reshape(-1, wpw))
        return first, jnp.int32(1)

    monkeypatch.setattr(fch, "gear_window_first_checked", fake_checked)
    monkeypatch.setenv("DAT_CDC_ROUTE", "fused1p")
    data = random.Random(23).randbytes(2 << 12)
    buf = np.zeros(-(-len(data) // 4) * 4, dtype=np.uint8)
    buf[: len(data)] = np.frombuffer(data, dtype=np.uint8)
    before = obs_metrics.snapshot()["counters"].get(
        "cdc.fused.crosscheck.refused", 0)
    got = rabin.candidates_words(buf.view("<u4"), len(data), avg_bits=8,
                                 tile_bytes=1 << 12, thin_bits=8)
    ref = rabin.host_thin(rabin.host_candidates(data, 8), 8)
    assert got.tolist() == ref
    after = obs_metrics.snapshot()["counters"].get(
        "cdc.fused.crosscheck.refused", 0)
    assert after == before + 1


# -- device single-residency pipeline -----------------------------------------


def test_device_pipeline_matches_host_routes(monkeypatch):
    monkeypatch.setenv("DAT_DEVICE_CDC", "1")
    monkeypatch.setenv("DAT_DEVICE_HASH", "1")
    rng = np.random.default_rng(31)
    buf = rng.integers(0, 256, 150_000, dtype=np.uint8)
    cuts_d, digs_d = content_digests(buf, avg_bits=10)
    monkeypatch.setenv("DAT_DEVICE_CDC", "0")
    monkeypatch.setenv("DAT_DEVICE_HASH", "0")
    cuts_h, digs_h = content_digests(buf, avg_bits=10)
    assert cuts_d == cuts_h
    assert np.array_equal(digs_d, digs_h)


def test_pack_extents_device_matches_host_pack():
    import jax.numpy as jnp

    from dat_replication_protocol_tpu.batch.feed import pack_ragged
    from dat_replication_protocol_tpu.ops.fused_cdc_hash_pallas import (
        pack_extents_device,
    )

    rng = np.random.default_rng(41)
    buf = rng.integers(0, 256, 5000, dtype=np.uint8)
    offs = np.array([0, 130, 1024, 2049], dtype=np.int64)
    lens = np.array([130, 894, 1025, 777], dtype=np.int64)
    nb = 16
    staged = np.zeros(-(-len(buf) // 4) * 4, dtype=np.uint8)
    staged[: len(buf)] = buf
    words = jnp.asarray(staged.view("<u4"))
    mh_d, ml_d, lens_d = pack_extents_device(words, offs, lens, nb)
    mh_h, ml_h, lens_h = pack_ragged(buf, offs, lens, nb)
    assert np.array_equal(np.asarray(mh_d), mh_h)
    assert np.array_equal(np.asarray(ml_d), ml_h)
    assert np.array_equal(np.asarray(lens_d), lens_h)


def test_merkle_root_host_matches_device_fold():
    from dat_replication_protocol_tpu.ops import merkle

    rng = np.random.default_rng(43)
    for n in (1, 2, 3, 5, 8, 100):
        digs = rng.integers(0, 256, (n, 32), dtype=np.uint8)
        leaves = [digs[i].tobytes() for i in range(n)]
        p = 1
        while p < n:
            p <<= 1
        padded = leaves + [b"\0" * 32] * (p - n)
        assert merkle.root_host(digs) == merkle.host_tree(padded)[-1][0]
    assert merkle.root_host(np.empty((0, 32), np.uint8)) == b"\0" * 32


# -- ptr-array native hash entry (ADVICE r5 satellite) ------------------------


def test_hash_many_list_ptr_entry_parity():
    if not native.available():
        pytest.skip("native library unavailable")
    rng = random.Random(5)
    payloads = [rng.randbytes(rng.randrange(0, 5000)) for _ in range(300)]
    payloads += [b"", b"x", b"y" * 128, b"z" * 129, b"w" * 256]
    out = native.hash_many_list(payloads)
    if out is None:
        pytest.skip("fastpath extension unavailable")
    for i, p in enumerate(payloads):
        assert out[i].tobytes() == hashlib.blake2b(
            p, digest_size=32).digest(), i
    # and against the extent-based engine over a joined buffer
    lens = np.array([len(p) for p in payloads], dtype=np.int64)
    offs = np.cumsum(lens) - lens
    joined = np.frombuffer(b"".join(payloads), np.uint8)
    assert np.array_equal(out, native.hash_many(joined, offs, lens))


# -- donated dispatch + pipelined readback ------------------------------------


def test_donated_batch_path_byte_exact(monkeypatch):
    import warnings

    from dat_replication_protocol_tpu.ops.blake2b import (
        blake2b_batch,
        donation_supported,
    )

    payloads = [random.Random(9).randbytes(n) for n in (0, 1, 128, 1000)]
    ref = [hashlib.blake2b(p, digest_size=32).digest() for p in payloads]
    monkeypatch.setenv("DAT_DONATE", "0")
    assert not donation_supported()
    assert blake2b_batch(payloads) == ref
    monkeypatch.setenv("DAT_DONATE", "1")
    assert donation_supported()
    with warnings.catch_warnings():
        # CPU jax ignores donation with a warning; the routed default
        # (donation_supported) never takes this path on CPU — the
        # override exists exactly so the donated program is testable
        warnings.simplefilter("ignore")
        assert blake2b_batch(payloads) == ref


def test_pipeline_prefetches_d2h_before_deliver():
    """Part 3 of the tentpole: dispatching batch N+1 starts batch N's
    digest readback (start_d2h) BEFORE any deliver blocks on it."""
    from dat_replication_protocol_tpu.backend.tpu_backend import (
        DigestPipeline,
    )

    events = []
    ids = iter(range(100))

    def hash_begin(payloads):
        batch_id = next(ids)
        events.append(("dispatch", batch_id))

        def collect():
            events.append(("collect", batch_id))
            return [hashlib.blake2b(p, digest_size=32).digest()
                    for p in payloads]

        def start_d2h():
            if ("start_d2h", batch_id) not in events:
                events.append(("start_d2h", batch_id))

        collect.start_d2h = start_d2h
        return collect

    pipe = DigestPipeline(hash_begin=hash_begin, max_batch=1,
                          max_inflight=2)
    got = []
    for i in range(3):
        pipe.submit(b"payload-%d" % i, got.append)
    pipe.flush()
    assert len(got) == 3
    # batch 0's readback started when batch 1 was dispatched — well
    # before anything collected it
    assert events.index(("start_d2h", 0)) < events.index(("collect", 0))
    assert events.index(("start_d2h", 0)) > events.index(("dispatch", 1)) - 2
    # every batch's readback was started before its collect
    for b in range(3):
        assert events.index(("start_d2h", b)) < events.index(("collect", b))


def test_dispatch_span_opens_before_prior_deliver_closes(obs_enabled):
    """The acceptance trace evidence: with the pipelined readback, the
    device.dispatch span of batch N+1 OPENS before the device.deliver
    span of batch N closes (h2d rides under compute, readback under the
    next submit)."""
    from dat_replication_protocol_tpu.backend.tpu_backend import (
        DigestPipeline,
    )
    from dat_replication_protocol_tpu.obs.tracing import SPANS

    pipe = DigestPipeline(max_batch=1, max_inflight=2)
    got = []
    for i in range(4):
        pipe.submit(b"p%d" % i, got.append)
    pipe.flush()
    assert len(got) == 4
    dispatches = SPANS.spans("device.dispatch")
    delivers = SPANS.spans("device.deliver")
    assert len(dispatches) == 4 and len(delivers) == 4
    # deliver of batch 0 happens inside dispatch of batch 2 (inflight
    # bound 2): dispatch[2] opened before deliver[0] closed
    d2_open = dispatches[2]["ts"]
    d0_close = delivers[0]["ts"] + delivers[0]["dur"]
    assert d2_open <= d0_close


def test_feed_h2d_overlap_counter(obs_enabled):
    from dat_replication_protocol_tpu.batch.feed import hash_extents
    from dat_replication_protocol_tpu.obs import metrics as obs_metrics

    rng = np.random.default_rng(51)
    buf = rng.integers(0, 256, 64 * 4096, dtype=np.uint8)
    offs = np.arange(64, dtype=np.int64) * 4096
    lens = np.full(64, 4096, dtype=np.int64)
    # tiny pipeline budget: many chunks, uploads staged while earlier
    # dispatches are still in flight
    digs = hash_extents(buf, offs, lens, pipeline_bytes=1 << 14)
    assert len(digs) == 64
    snap = obs_metrics.snapshot()["counters"]
    assert snap.get("device.h2d.overlap", 0) > 0
    assert digs[0].tobytes() == hashlib.blake2b(
        buf[:4096].tobytes(), digest_size=32).digest()
