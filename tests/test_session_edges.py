"""Coverage for the reference suite's gaps (SURVEY §4): destroy/error paths,
unknown-type protocol error, finalize callbacks, multi-byte varints (frames
>127 bytes), chunk-boundary splits mid-header / mid-change, backpressure
timing, ordering invariants, counters."""

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.session.encoder import BlobLengthError
from dat_replication_protocol_tpu.wire import ProtocolError, frame, TYPE_CHANGE
from dat_replication_protocol_tpu.wire.change_codec import Change, encode_change


def wire_bytes(build):
    """Run ``build(encoder)`` and return everything the encoder produced."""
    e = protocol.encode()
    build(e)
    e.finalize()
    out = bytearray()
    while True:
        data = e.read()
        if data is None:
            return bytes(out)
        if not data:
            return bytes(out)
        out += data


def feed_bytewise(d, data):
    for i in range(len(data)):
        d.write(data[i : i + 1])


def test_large_frame_multibyte_varint_and_split_feeds():
    # a change with a 4 KiB value ⇒ frame length needs a multi-byte varint
    big = bytes(range(256)) * 16
    data = wire_bytes(
        lambda e: e.change({"key": "k" * 200, "change": 1, "from": 0, "to": 1, "value": big})
    )
    assert len(data) > 4096  # really is a multi-byte-varint frame

    got = []
    d = protocol.decode()
    d.change(lambda c, done: (got.append(c), done()))
    feed_bytewise(d, data)  # worst-case chunk boundaries: 1 byte at a time
    d.end()
    assert d.finished
    assert got[0].value == big and got[0].key == "k" * 200


def test_blob_split_across_every_boundary():
    payload = bytes(range(251)) * 5  # 1255 bytes
    data = wire_bytes(lambda e: (e.blob(len(payload)).end(payload)))
    for chunk_size in (1, 2, 3, 7, 128, 1024):
        got = []
        d = protocol.decode()
        d.blob(lambda b, done: b.collect(lambda x: (got.append(x), done())))
        for i in range(0, len(data), chunk_size):
            d.write(data[i : i + chunk_size])
        d.end()
        assert got == [payload], f"chunk_size={chunk_size}"


def test_unknown_type_id_is_protocol_error():
    # reference: decode.js:159-161
    d = protocol.decode()
    errs = []
    d.on_error(lambda e: errs.append(e))
    d.write(frame(7, b"xx"))
    assert d.destroyed
    assert isinstance(errs[0], ProtocolError)
    assert "unknown type" in str(errs[0])


def test_overlong_header_varint_is_protocol_error():
    # a 10-byte varint encoding >= 2^64 must destroy with ProtocolError,
    # not leak ValueError out of write()
    d = protocol.decode()
    errs = []
    d.on_error(lambda e: errs.append(e))
    d.write(b"\x80" * 9 + b"\x7f" + bytes([TYPE_CHANGE]))
    assert d.destroyed
    assert isinstance(errs[0], ProtocolError)


def test_huge_frame_length_waits_for_data():
    # 2^63-byte claimed frame: the streaming decoder just waits for more
    # bytes (never crashes, never goes negative)
    from dat_replication_protocol_tpu.wire.varint import encode_uvarint

    d = protocol.decode()
    d.write(encode_uvarint(1 << 63) + bytes([TYPE_CHANGE]) + b"x" * 64)
    assert not d.destroyed and not d.finished


def test_corrupt_change_payload_is_protocol_error():
    d = protocol.decode()
    errs = []
    d.on_error(lambda e: errs.append(e))
    d.write(frame(TYPE_CHANGE, b"\x18\x01"))  # missing required fields
    assert d.destroyed and isinstance(errs[0], ProtocolError)


def test_header_too_long_is_protocol_error():
    d = protocol.decode()
    errs = []
    d.on_error(lambda e: errs.append(e))
    d.write(b"\xff" * 11)
    assert d.destroyed and isinstance(errs[0], ProtocolError)


def test_end_mid_frame_is_protocol_error():
    d = protocol.decode()
    errs = []
    d.on_error(lambda e: errs.append(e))
    d.write(frame(TYPE_CHANGE, encode_change(Change(key="k", change=1, from_=0, to=1)))[:-2])
    d.end()
    assert d.destroyed and isinstance(errs[0], ProtocolError)


def test_finalize_callback_order():
    # finalize must run after all frames are consumed, before finish
    # (reference: decode.js:124-142)
    e = protocol.encode()
    d = protocol.decode()
    order = []
    d.change(lambda c, done: (order.append("change"), done()))
    d.finalize(lambda done: (order.append("finalize"), done()))
    d.on_finish(lambda: order.append("finish"))

    e.change({"key": "k", "change": 1, "from": 0, "to": 1})
    e.finalize(lambda: order.append("enc-flushed"))
    protocol.pipe(e, d)

    # encoder-side flush fires when bytes are *pulled* (the reference times it
    # to the Readable drain, encode.js:147-151), so it precedes the decoder's
    # handler; finalize runs after all frames, before finish.
    assert order == ["enc-flushed", "change", "finalize", "finish"]


def test_decoder_default_handlers_never_deadlock():
    # reference: decode.js:50-61 — nothing registered: changes dropped,
    # blobs drained, finalize auto-acked.
    e = protocol.encode()
    d = protocol.decode()
    e.change({"key": "k", "change": 1, "from": 0, "to": 1})
    b = e.blob(5)
    b.end(b"12345")
    e.finalize()
    protocol.pipe(e, d)
    assert d.finished
    assert d.changes == 1 and d.blobs == 1


def test_deferred_done_backpressure_and_drain():
    """A held `done` must stall the decoder (write -> False) and parsing must
    resume exactly where it stopped when released (reference: decode.js:87-99,168)."""
    e = protocol.encode()
    d = protocol.decode()
    got = []
    held = []

    d.change(lambda c, done: (got.append(c.key), held.append(done)))

    for i in range(3):
        e.change({"key": f"k{i}", "change": i, "from": 0, "to": 1})
    e.finalize()
    data = bytearray()
    while (chunk := e.read()) not in (None, b""):
        data += chunk

    assert d.write(data) is False  # stalled on first change's done
    assert got == ["k0"]
    held.pop()()  # release first
    assert got == ["k0", "k1"]
    held.pop()()
    assert got == ["k0", "k1", "k2"]
    d.end()
    assert not d.finished  # still one outstanding
    held.pop()()
    assert d.finished


def test_blob_pause_resume_backpressure():
    e = protocol.encode()
    d = protocol.decode()
    chunks = []
    readers = []

    def on_blob(blob, done):
        readers.append(blob)
        blob.on_data(lambda c: (chunks.append(c), blob.pause()))
        blob.on_end(done)

    d.blob(on_blob)
    b = e.blob(6)
    b.write(b"ab")
    b.write(b"cd")
    b.end(b"ef")
    e.finalize()
    p = protocol.pipe(e, d, chunk_size=2)
    # paused after first delivered chunk
    assert chunks and not d.finished
    while not d.finished:
        readers[0].resume()
        p.pump()
    assert b"".join(chunks) == b"abcdef"


def test_encoder_flush_callbacks_fire_on_pull():
    e = protocol.encode()
    fired = []
    e.change({"key": "k", "change": 1, "from": 0, "to": 1}, on_flush=lambda: fired.append("change"))
    b = e.blob(3, on_flush=lambda: fired.append("blob"))
    b.end(b"xyz")
    assert fired == []  # nothing pulled yet
    e.read()
    assert fired == ["change", "blob"]


def test_changes_parked_behind_all_open_blobs():
    """Changes submitted while two blobs are open arrive after BOTH."""
    e = protocol.encode()
    d = protocol.decode()
    order = []
    d.blob(lambda blob, done: blob.collect(lambda x: (order.append(x), done())))
    d.change(lambda c, done: (order.append(c.key), done()))

    b1 = e.blob(1)
    b2 = e.blob(1)
    e.change({"key": "parked", "change": 1, "from": 0, "to": 1})
    b1.end(b"a")
    e.change({"key": "parked2", "change": 2, "from": 0, "to": 1})  # b2 still open
    b2.end(b"b")
    e.finalize()
    protocol.pipe(e, d)
    assert order == [b"a", b"b", "parked", "parked2"]


def test_blob_fifo_wire_order_with_interleaved_writes():
    e = protocol.encode()
    b1 = e.blob(4)
    b2 = e.blob(4)
    b2.write(b"BB")
    b1.write(b"aa")
    b2.end(b"BB")
    b1.end(b"aa")
    e.finalize()
    d = protocol.decode()
    got = []
    d.blob(lambda blob, done: blob.collect(lambda x: (got.append(x), done())))
    protocol.pipe(e, d)
    assert got == [b"aaaa", b"BBBB"]  # creation order, not completion order


def test_blob_overflow_destroys_session():
    e = protocol.encode()
    b = e.blob(3)
    with pytest.raises(BlobLengthError):
        b.write(b"toolong")
    assert e.destroyed


def test_blob_short_end_destroys_session():
    e = protocol.encode()
    b = e.blob(10)
    b.write(b"abc")
    with pytest.raises(BlobLengthError):
        b.end()
    assert e.destroyed


def test_blob_zero_length_rejected_at_encoder():
    # reference throws on falsy length (reference: encode.js:79)
    e = protocol.encode()
    with pytest.raises(ValueError):
        e.blob(0)


def test_destroy_cascades_encoder():
    e = protocol.encode()
    errs = []
    e.on_error(lambda err: errs.append(err))
    b1 = e.blob(5)
    b2 = e.blob(5)
    b1.destroy(RuntimeError("boom"))
    assert e.destroyed and b2.destroyed
    assert isinstance(errs[0], RuntimeError)


def test_destroy_cascades_decoder_blob():
    e = protocol.encode()
    d = protocol.decode()
    readers = []
    d.blob(lambda blob, done: readers.append(blob))
    b = e.blob(4)
    b.write(b"ab")
    # feed header + partial payload so a reader exists
    d.write(e.read())
    readers[0].destroy(RuntimeError("boom"))
    assert d.destroyed


def test_counters_match_both_sides():
    # counters parity (reference: encode.js:51-53, decode.js:68-70)
    e = protocol.encode()
    d = protocol.decode()
    d.blob(lambda blob, done: blob.on_end(done))
    e.change({"key": "k", "change": 1, "from": 0, "to": 1})
    blob = e.blob(8)
    blob.end(b"01234567")
    e.change({"key": "k2", "change": 2, "from": 1, "to": 2})
    e.finalize()
    protocol.pipe(e, d)
    assert e.changes == d.changes == 2
    assert e.blobs == d.blobs == 1
    assert e.bytes == d.bytes > 0


def test_write_after_finalize_raises():
    e = protocol.encode()
    e.finalize()
    with pytest.raises(Exception):
        e.change({"key": "k", "change": 1, "from": 0, "to": 1})


def test_finalize_with_open_blob_raises():
    e = protocol.encode()
    e.blob(3)
    with pytest.raises(Exception):
        e.finalize()


def test_many_frames_stress_roundtrip():
    e = protocol.encode(high_water=1 << 20)
    d = protocol.decode()
    got = []
    d.change(lambda c, done: (got.append(c), done()))
    d.blob(lambda blob, done: blob.collect(lambda x: (got.append(x), done())))

    import random

    rng = random.Random(1234)
    sent = []
    p = protocol.pipe(e, d, chunk_size=777)
    for i in range(500):
        if rng.random() < 0.3:
            n = rng.randrange(1, 2000)
            payload = rng.randbytes(n)
            b = e.blob(n)
            # write in random slices
            j = 0
            while j < n:
                step = rng.randrange(1, n - j + 1)
                b.write(payload[j : j + step])
                j += step
            b.end()
            sent.append(payload)
        else:
            c = Change(
                key=f"key-{i}",
                change=i,
                from_=i,
                to=i + 1,
                value=rng.randbytes(rng.randrange(0, 64)),
                subset="" if rng.random() < 0.5 else f"s{i}",
            )
            sent.append(c)
            e.change(c)
    e.finalize()
    p.pump()
    assert d.finished
    # decoded changes have ''/b'' defaults; encoded with subset='' roundtrips
    norm = [
        Change(c.key, c.change, c.from_, c.to, c.value or b"", c.subset or "")
        if isinstance(c, Change)
        else c
        for c in sent
    ]
    assert got == norm
