"""Conformance port of the reference suite (reference: test/basic.js:1-127).

Each test mirrors one tape test: construct a real Encoder and Decoder, pipe
them together in-process, and assert the decoded callbacks — loopback piping
is the fake backend, exactly as in the reference.

Parametrized over both backends: the north-star contract is that these
scenarios pass UNMODIFIED with ``backend='tpu'`` (the digest pipeline
rides alongside; wire behavior is identical).
"""

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.wire.change_codec import Change


@pytest.fixture(params=["host", "tpu"])
def ends(request):
    return (protocol.encode(backend=request.param),
            protocol.decode(backend=request.param))


def test_encode_decode_changes(ends):
    # reference: test/basic.js:5-30
    e, d = ends
    got = []

    d.change(lambda change, done: (got.append(change), done()))

    e.change({"key": "key", "from": 0, "to": 1, "change": 1, "value": b"hello"})
    e.finalize()
    protocol.pipe(e, d)

    assert got == [
        Change(key="key", from_=0, to=1, change=1, value=b"hello", subset="")
    ]


def test_encode_decode_blob(ends):
    # reference: test/basic.js:32-51
    e, d = ends
    got = []

    def on_blob(blob, done):
        blob.collect(lambda data: (got.append(data), done()))

    d.blob(on_blob)

    blob = e.blob(11)
    blob.write(b"hello ")
    blob.write(b"world")
    blob.end()
    e.finalize()
    protocol.pipe(e, d)

    assert got == [b"hello world"]
    assert len(got[0]) == 11


def test_encode_decode_mixed_blobs(ends):
    # reference: test/basic.js:53-84 — the concurrency test: two blobs created
    # before either is written, writes interleaved; both must arrive intact
    # and in creation order (exercises cork/uncork, reference: encode.js:87-94).
    e, d = ends
    expects = [b"hello world", b"HELLO WORLD"]
    got = []

    def on_blob(blob, done):
        blob.collect(lambda data: (got.append(data), done()))

    d.blob(on_blob)

    b1 = e.blob(11)
    b2 = e.blob(11)
    b1.write(b"hello ")
    b2.write(b"HELLO ")
    b1.write(b"world")
    b2.write(b"WORLD")
    b1.end()
    b2.end()
    e.finalize()
    protocol.pipe(e, d)

    assert got == expects


def test_encode_decode_blob_and_changes(ends):
    # reference: test/basic.js:86-127 — a change submitted while a blob is
    # open must be parked and arrive after the blob (reference: encode.js:104-107).
    e, d = ends
    order = []

    def on_blob(blob, done):
        blob.collect(lambda data: (order.append(("blob", data)), done()))

    def on_change(change, done):
        order.append(("change", change))
        done()

    d.blob(on_blob)
    d.change(on_change)

    blob = e.blob(11)
    blob.write(b"hello ")
    blob.write(b"world")
    e.change({"key": "key", "from": 0, "to": 1, "change": 1, "value": b"hello"})
    blob.end()
    e.finalize()
    protocol.pipe(e, d)

    assert order == [
        ("blob", b"hello world"),
        ("change", Change(key="key", from_=0, to=1, change=1, value=b"hello", subset="")),
    ]
