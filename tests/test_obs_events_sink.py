"""EventLog sink edge cases (ISSUE 4 satellites): records reach a fd
sink whole or not at all.

Uses REAL non-blocking pipes — filling a pipe is the honest way to
produce EAGAIN, and a record larger than the remaining capacity is the
honest way to produce a partial write — so the tests exercise exactly
the syscall behavior production sees, with no monkeypatched os.write.
"""

from __future__ import annotations

import json
import os
import threading

from dat_replication_protocol_tpu.obs import metrics as obs_metrics
from dat_replication_protocol_tpu.obs.events import EventLog


def _nonblocking_pipe():
    r, w = os.pipe()
    os.set_blocking(w, False)
    return r, w


def _fill_pipe(w: int) -> int:
    """Write until EAGAIN; returns bytes stuffed."""
    total = 0
    pad = b"x" * 65536
    while True:
        try:
            total += os.write(w, pad)
        except BlockingIOError:
            return total


def _drain(r: int) -> bytes:
    os.set_blocking(r, False)
    out = b""
    while True:
        try:
            chunk = os.read(r, 65536)
        except BlockingIOError:
            return out
        if not chunk:
            return out
        out += chunk


def test_dead_fd_swallow_counts_and_never_raises(obs_enabled):
    r, w = os.pipe()
    os.close(r)  # EPIPE on write (Python maps it to BrokenPipeError)
    log = EventLog(capacity=8)
    log.attach_sink(w)
    log.emit("sink.dead", i=1)
    log.emit("sink.dead", i=2)
    os.close(w)
    # the session never noticed; the ring kept everything; the sink
    # accounted for each record it dropped whole
    assert log.count("sink.dead") == 2
    assert log.sink_dropped == 2


def test_eagain_before_first_byte_drops_record_atomically(obs_enabled):
    r, w = _nonblocking_pipe()
    try:
        log = EventLog(capacity=8)
        log.attach_sink(w)
        _fill_pipe(w)
        mark = _drain(r)  # note: pipe now empty again
        _fill_pipe(w)  # refill: zero room for the next record
        log.emit("sink.full", i=1)
        assert log.sink_dropped == 1
        drained = _drain(r)
        # nothing of the record reached the fd — no torn line, and the
        # sink did NOT latch: with room again, the next record lands
        assert b"sink.full" not in drained
        log.emit("sink.retry", i=2)
        rec = json.loads(_drain(r).decode())
        assert rec["event"] == "sink.retry"
        assert len(mark) > 0  # sanity: the pipe really was full before
    finally:
        os.close(r)
        os.close(w)


def test_eagain_mid_record_latches_sink_dead_and_counts(obs_enabled):
    r, w = _nonblocking_pipe()
    try:
        log = EventLog(capacity=8)
        log.attach_sink(w)
        filled = _fill_pipe(w)
        # leave exactly 64 bytes of room: the next (much larger) record
        # MUST tear mid-line, and with nobody draining, the bounded
        # retry expires and the sink latches dead
        os.read(r, 64)
        log.emit("sink.torn", pad="y" * 4096)
        assert log.sink_dropped == 1
        # latched: later records write NOTHING after the torn fragment
        log.emit("sink.after", i=1)
        assert log.sink_dropped == 2
        drained = _drain(r)
        assert b"sink.after" not in drained
        # the stream ends at the tear: either the kernel accepted a
        # 64-byte prefix of the record before EAGAIN (a torn final line
        # a JSONL consumer discards harmlessly) or it refused the
        # oversized write outright with zero bytes (some kernels only
        # tear at PIPE_BUF granularity) — both leave no complete record
        torn = len(drained) - (filled - 64)
        assert torn in (0, 64)
        if torn:
            assert not drained.endswith(b"\n")
        # the ring itself kept both records (the sink is best-effort)
        assert log.count("sink.torn") == 1 and log.count("sink.after") == 1
        # re-attaching clears the latch
        log.attach_sink(w)
        _drain(r)
        log.emit("sink.reborn", i=1)
        assert json.loads(_drain(r).decode())["event"] == "sink.reborn"
    finally:
        os.close(r)
        os.close(w)


def test_partial_writes_complete_the_line_when_the_pipe_drains(obs_enabled):
    """A record bigger than the free capacity finishes via the bounded
    retry loop when a consumer drains concurrently — one parseable
    line, nothing dropped."""
    r, w = _nonblocking_pipe()
    collected = bytearray()
    stop = threading.Event()

    def consumer():
        os.set_blocking(r, True)
        while not stop.is_set() or True:
            try:
                chunk = os.read(r, 65536)
            except OSError:
                return
            if not chunk:
                return
            collected.extend(chunk)

    t = threading.Thread(target=consumer, daemon=True)
    try:
        log = EventLog(capacity=4)
        log.attach_sink(w)
        big = "z" * (256 * 1024)  # ≫ pipe capacity: guaranteed partial
        t.start()
        log.emit("sink.big", pad=big)
        assert log.sink_dropped == 0
    finally:
        stop.set()
        os.close(w)  # EOF for the consumer
        t.join(5)
        os.close(r)
    lines = bytes(collected).decode().splitlines()
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["event"] == "sink.big" and rec["fields"]["pad"] == big


def test_sink_attached_mid_storm_yields_only_whole_lines(obs_enabled):
    """Threads hammering emit() while the sink attaches midway: every
    line on the sink parses, and post-attach records are contiguous
    (the sink lock serializes whole records, never characters)."""
    log = EventLog(capacity=4096)

    class Sink:
        def __init__(self):
            self.chunks = []

        def write(self, s):
            self.chunks.append(s)

    sink = Sink()
    N, T = 200, 4
    start = threading.Barrier(T + 1)

    def storm(tid):
        start.wait()
        for i in range(N):
            log.emit("storm.ev", tid=tid, i=i)

    threads = [threading.Thread(target=storm, args=(k,)) for k in range(T)]
    for t in threads:
        t.start()
    start.wait()
    log.attach_sink(sink)  # mid-storm
    for t in threads:
        t.join()
    for chunk in sink.chunks:
        rec = json.loads(chunk)  # each write() call is one whole record
        assert rec["event"] == "storm.ev"
    recs = [json.loads(c) for c in sink.chunks]
    # every mirrored record exactly once — but NOT globally seq-sorted:
    # seq is assigned under the ring lock while sink I/O serializes on
    # its own lock (the documented two-lock design), so two racing
    # emitters may land on the sink in either order.  Per-THREAD order
    # IS program order and must hold.
    seqs = [r["seq"] for r in recs]
    assert len(set(seqs)) == len(seqs)
    for tid in range(T):
        own = [r["fields"]["i"] for r in recs if r["fields"]["tid"] == tid]
        assert own == sorted(own)


def test_clear_keeps_seq_monotonic(obs_enabled):
    log = EventLog(capacity=8)
    log.emit("seq.a")
    log.emit("seq.b")
    last = log.events()[-1]["seq"]
    log.clear()
    assert log.events() == [] and log.dropped == 0
    log.emit("seq.c")
    assert log.events()[0]["seq"] == last + 1  # never reused after clear


def test_file_object_sink_failure_counts_and_session_survives(obs_enabled):
    log = EventLog(capacity=4)

    class Dying:
        def write(self, s):
            raise ValueError("closed file")

    log.attach_sink(Dying())
    log.emit("sink.objdead", i=1)
    assert log.count("sink.objdead") == 1
    assert log.sink_dropped == 1


def test_gate_off_means_no_sink_traffic():
    assert not obs_metrics.OBS.on
    log = EventLog(capacity=4)
    written = []
    log.attach_sink(type("S", (), {"write": lambda self, s: written.append(s)})())
    log.emit("dark.event")
    assert written == [] and log.events() == []
