"""Chaos parity for the kernel-bypass wire pump (ISSUE 14).

The contract that makes DAT_PUMP a ROUTE and not a fork: for the same
wire byte stream — including streams a FaultPlan has already mangled —
the native batched-syscall pump and the Python reference pump produce
BYTE-IDENTICAL sessions: deliveries (changes, blob contents), digest
streams, checkpoints, and structured errors (same frame index, same
wire offset, same message).  20-seed sweep in tier 1, 100-seed soak in
the slow tier, plus a re-segmentation fuzz that forces batch frames to
straddle pump-batch boundaries.

Faults are materialized ONCE per seed (the FaultyReader applied to the
source wire, segmentation preserved) and the identical segment
sequence is then fed to both routes over a real socketpair — so any
divergence is the pump's, not the fault injector's clock.
"""

from __future__ import annotations

import io
import os
import socket
import threading

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.session import pump
from dat_replication_protocol_tpu.session.faults import (
    FaultPlan,
    FaultyReader,
    TransportFault,
)
from dat_replication_protocol_tpu.wire.framing import CAP_CHANGE_BATCH

SWEEP_SEEDS = 20
SOAK_SEEDS = 100


def _build_wire(seed: int) -> bytes:
    """A mixed session wire: bulk per-record changes, columnar batch
    frames on odd seeds (negotiated), a couple of blobs."""
    caps = CAP_CHANGE_BATCH if seed % 2 else 0
    e = protocol.encode(peer_caps=caps) if caps else protocol.encode()
    rows = 400 + (seed * 37) % 300
    e.change_many([
        {"key": f"k{seed}-{j:05d}", "change": j, "from": j, "to": j + 1,
         "value": bytes([j % 251]) * (j % 90)}
        for j in range(rows)
    ])
    b = e.blob(30_000 + seed * 13)
    b.write(bytes(30_000 + seed * 13))
    b.end()
    e.change({"key": f"tail-{seed}", "change": 1, "from": 0, "to": 1})
    e.finalize()
    parts = []
    while True:
        d = e.read(1 << 20)
        if d is None:
            break
        parts.append(d)
    return b"".join(parts)


def _materialize_faulted(wire: bytes, plan: FaultPlan):
    """Run the fault injector over ``wire`` once and keep the exact
    segment sequence it delivered (plus whether the stream died on a
    TransportFault instead of clean EOF).

    Timing faults (stall/latency) are zeroed first: a kernel stream
    erases segment boundaries anyway, so parity is about CONTENT — the
    sleeps would only slow the sweep (tier-1 runtime budget)."""
    plan.stall_s = 0.0
    plan.latency_prob = 0.0
    src = io.BytesIO(wire)
    fr = FaultyReader(lambda n: src.read(n), plan)
    segments = []
    dropped = False
    while True:
        try:
            d = fr.read(65536)
        except TransportFault:
            dropped = True
            break
        if not d:
            break
        segments.append(d)
    # coalesce for the feeder: send() boundaries are invisible to the
    # receiving pump (stream semantics), and one-byte sendalls at
    # max_segment=1 would pay ~wire_len syscalls per route
    whole = b"".join(segments)
    return [whole[i:i + (256 << 10)]
            for i in range(0, len(whole), 256 << 10)], dropped


def _run_route(route: str, segments, monkeypatch_env) -> dict:
    """One digest session over a socketpair on ``route``; returns the
    full observable surface for comparison."""
    monkeypatch_env.setenv("DAT_PUMP", route)
    a, b = socket.socketpair()
    try:
        dec = protocol.decode(backend="tpu")
        out = {"changes": [], "blobs": [], "digests": [], "errors": []}
        dec.change(lambda c, done: (out["changes"].append(
            (c.key, c.change, c.from_, c.to, c.value, c.subset)), done()))
        dec.blob(lambda blob, done: blob.collect(
            lambda data: (out["blobs"].append(data), done())))
        dec.on_digest(lambda kind, seq, dig:
                      out["digests"].append((kind, seq, dig)))
        dec.on_error(lambda err: out["errors"].append(err))

        def feed() -> None:
            try:
                for seg in segments:
                    a.sendall(seg)
            except OSError:
                pass  # decoder destroyed mid-stream: receiver closed
            try:
                a.shutdown(socket.SHUT_WR)
            except OSError:
                pass

        t = threading.Thread(target=feed, daemon=True)
        t.start()
        try:
            pump.recv_pump(dec, b.fileno())
        except OSError:
            pass  # transport died under the pump: the destroy cascade
        b.close()  # unblock a feeder parked on a full socket
        t.join(30)
        ck = dec.checkpoint(emit_event=False)
        out["final"] = (dec.finished, dec.destroyed, dec.bytes,
                        dec.changes, dec.blobs)
        out["checkpoint"] = (ck.wire_offset, ck.frame, ck.row,
                             ck.blob_offset)
        out["errors"] = [
            (type(err).__name__, getattr(err, "frame", None),
             getattr(err, "offset", None), str(err))
            for err in out["errors"]
        ]
        return out
    finally:
        a.close()
        b.close()


def _assert_routes_identical(seed: int, segments, monkeypatch) -> None:
    py = _run_route("python", segments, monkeypatch)
    nat = _run_route("native", segments, monkeypatch)
    for field in ("changes", "blobs", "digests", "errors", "final",
                  "checkpoint"):
        assert py[field] == nat[field], (
            f"seed {seed}: pump routes diverge on {field}: "
            f"python={py[field]!r:.300} native={nat[field]!r:.300}")


def _sweep(seed: int, monkeypatch) -> None:
    wire = _build_wire(seed)
    plan = FaultPlan.for_sweep(seed, len(wire))
    segments, _dropped = _materialize_faulted(wire, plan)
    _assert_routes_identical(seed, segments, monkeypatch)


@pytest.mark.parametrize("seed", range(SWEEP_SEEDS))
def test_pump_parity_under_faults(seed, monkeypatch):
    _sweep(seed, monkeypatch)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(SWEEP_SEEDS, SOAK_SEEDS))
def test_pump_parity_soak(seed, monkeypatch):
    _sweep(seed, monkeypatch)


def test_pump_parity_flip_is_one_structured_error(monkeypatch):
    """A flipped byte must fail STRUCTURED — one ProtocolError with the
    same (frame, offset) coordinates on both routes, never a hang and
    never divergent content."""
    wire = _build_wire(3)
    plan = FaultPlan(seed=9, flip_at=len(wire) // 3, flip_mask=0x40,
                     max_segment=1024)
    segments, _ = _materialize_faulted(wire, plan)
    py = _run_route("python", segments, monkeypatch)
    nat = _run_route("native", segments, monkeypatch)
    assert py["errors"] == nat["errors"]
    # content before the corrupt frame still delivered identically
    assert py["changes"] == nat["changes"]
    assert py["digests"] == nat["digests"]


def test_pump_parity_truncation_checkpoint(monkeypatch):
    """A truncated stream ends both routes at the same checkpoint (the
    resume point a reconnect would pay back to) with the same
    mid-frame error."""
    wire = _build_wire(5)
    plan = FaultPlan(seed=2, truncate_at=(len(wire) * 2) // 3)
    segments, _ = _materialize_faulted(wire, plan)
    _assert_routes_identical(5, segments, monkeypatch)


def test_pump_parity_resume_exactly_once(monkeypatch):
    """Truncate mid-blob, then resume from the checkpoint through the
    NATIVE pump: the reassembled session is byte-identical to an
    unfaulted Python-pump run — every change and blob byte delivered
    exactly once across the reconnect."""
    wire = _build_wire(7)
    clean = _run_route("python", [wire], monkeypatch)
    assert clean["final"][0] and not clean["final"][1]

    monkeypatch.setenv("DAT_PUMP", "native")
    cut = (len(wire) * 3) // 5
    dec = protocol.decode(backend="tpu")
    out = {"changes": [], "blobs": [], "digests": []}
    dec.change(lambda c, done: (out["changes"].append(
        (c.key, c.change, c.from_, c.to, c.value, c.subset)), done()))
    dec.blob(lambda blob, done: blob.collect(
        lambda data: (out["blobs"].append(data), done())))
    dec.on_digest(lambda kind, seq, dig:
                  out["digests"].append((kind, seq, dig)))

    def feed_conn(payload: bytes) -> None:
        a, b = socket.socketpair()
        try:
            t = threading.Thread(
                target=lambda: (a.sendall(payload),
                                a.shutdown(socket.SHUT_WR)),
                daemon=True)
            t.start()
            # a reconnecting transport: EOF here is connection loss,
            # not session end — the driver (not the pump) owns end()
            rd = pump.pump_reader(b.fileno())
            while True:
                d = rd(65536)
                if not d:
                    break
                dec.write(d)
            t.join(10)
        finally:
            a.close()
            b.close()

    feed_conn(wire[:cut])
    ck = dec.checkpoint(emit_event=False)
    assert 0 < ck.wire_offset <= cut
    # the sender replays from the checkpoint (the journal contract)
    feed_conn(wire[ck.wire_offset:])
    dec.end()
    assert dec.finished and not dec.destroyed
    assert out["changes"] == clean["changes"]
    assert out["blobs"] == clean["blobs"]
    assert out["digests"] == clean["digests"]


def test_pump_parity_resegmented_batch_frames(monkeypatch):
    """Re-segmentation fuzz across columnar batch frames: split the
    same wire at adversarial boundaries (1-byte tail, mid-header,
    mid-column) and require identical sessions from both routes."""
    import random

    wire = _build_wire(9)  # odd seed: columnar ChangeBatch frames
    for trial in range(6):
        rng = random.Random(trial)
        segments = []
        i = 0
        while i < len(wire):
            step = rng.choice([1, 2, 3, 17, 1024, 65536, 1 << 20])
            segments.append(wire[i:i + step])
            i += step
        _assert_routes_identical(900 + trial, segments, monkeypatch)
