"""Tier-1 gate: the shipped package carries zero datlint findings.

This is the analyzer's production run — the same invocation as
``python -m dat_replication_protocol_tpu.analysis`` — executed inside
the ordinary pytest suite so protocol-invariant regressions (a cursor
write-back dropped in a refactor, a new module-level env cache, a
drifted wire constant in one C file) fail CI like any other test,
with no extra pipeline step to forget.

A finding here means either real breakage (fix the code) or a new,
audited exception (add a ``# datlint: disable=<rule>`` with a
justification — see ANALYSIS.md for the syntax and the bar).
"""

from pathlib import Path

import dat_replication_protocol_tpu
from dat_replication_protocol_tpu.analysis import ALL_RULES, run_paths

PACKAGE_ROOT = Path(dat_replication_protocol_tpu.__file__).resolve().parent


def test_package_is_datlint_clean():
    findings = run_paths([PACKAGE_ROOT])
    assert findings == [], (
        "datlint findings in the shipped package:\n"
        + "\n".join(f.render() for f in findings)
    )


def test_registry_ships_the_incident_rules():
    # the gate is only as strong as the registry: losing a rule from
    # ALL_RULES would turn the clean-run above into a weaker check
    # without any test failing
    assert {r.name for r in ALL_RULES} >= {
        "cursor-coherence",
        "env-cache-policy",
        "unbounded-join",
        "bounded-wait",
        "jit-purity",
        "wire-constant-parity",
        "obs-discipline",
    }


def test_analyzer_actually_saw_the_protocol_stack():
    # guard against a silent scope regression (e.g. a _SKIP_DIRS typo
    # excluding session/): the decoder, both C sources, and the wire
    # layer must be in the analyzed file set
    from dat_replication_protocol_tpu.analysis.engine import Project

    project = Project.from_paths([PACKAGE_ROOT])
    names = {p.name for p in (s.path for s in project.sources)}
    assert {"decoder.py", "framing.py", "change_codec.py",
            "dat_native.cpp", "dat_fastpath.cpp"} <= names
