"""Tier-1 gate: the shipped package carries zero datlint findings.

This is the analyzer's production run — the same invocation as
``python -m dat_replication_protocol_tpu.analysis`` — executed inside
the ordinary pytest suite so protocol-invariant regressions (a cursor
write-back dropped in a refactor, a new module-level env cache, a
drifted wire constant in one C file, a blocking call creeping back
under a dispatcher lock) fail CI like any other test, with no extra
pipeline step to forget.

A finding here means either real breakage (fix the code) or a new,
audited exception (add a ``# datlint: disable=<rule>`` /
``allow-blocking-under-lock`` with a justification — see ANALYSIS.md
for the syntax and the bar).

Two more gates ride along (ISSUE 13):

* the whole-repo lint must fit a RUNTIME budget — tier-1 runtime is
  the active constraint, and a whole-program pass that regresses to
  quadratic blows the suite, not just itself;
* ``artifacts/lock_graph.json`` must byte-match a fresh render of the
  current tree — the event-loop refactor (ROADMAP item 2) diffs that
  artifact, so a lock added without regenerating it is a silent hole
  in the certification.
"""

import json
import os
from pathlib import Path

import dat_replication_protocol_tpu
from dat_replication_protocol_tpu.analysis import ALL_RULES, run_paths
from dat_replication_protocol_tpu.analysis.engine import Project, run_project

PACKAGE_ROOT = Path(dat_replication_protocol_tpu.__file__).resolve().parent
REPO_ROOT = PACKAGE_ROOT.parent

# generous vs the ~6 s observed (90 files, 13 rules): this catches a
# complexity regression (the index DFS going quadratic), not machine
# jitter.  Override for slow CI with DATLINT_BUDGET_S.
_BUDGET_S = float(os.environ.get("DATLINT_BUDGET_S", "45"))


def test_package_is_datlint_clean_within_budget():
    stats: dict = {}
    findings = run_project(Project.from_paths([PACKAGE_ROOT]), ALL_RULES,
                           stats)
    assert findings == [], (
        "datlint findings in the shipped package:\n"
        + "\n".join(f.render() for f in findings)
    )
    total = sum(stats.values())
    worst = max(stats.items(), key=lambda kv: kv[1])
    assert total < _BUDGET_S, (
        f"datlint whole-repo run took {total:.1f}s (budget {_BUDGET_S}s); "
        f"heaviest rule: {worst[0]} at {worst[1]:.1f}s — tier-1 runtime "
        f"is the active constraint (ROADMAP), trim the pass before "
        f"raising the budget")


def test_lock_graph_artifact_matches_the_tree(tmp_path):
    from dat_replication_protocol_tpu.analysis.__main__ import \
        write_lock_graph

    artifact = REPO_ROOT / "artifacts" / "lock_graph.json"
    assert artifact.exists(), (
        "artifacts/lock_graph.json is missing — regenerate with "
        "python -m dat_replication_protocol_tpu.analysis "
        "--lock-graph artifacts/lock_graph.json")
    # scratch render goes to the per-test tmp dir: a fixed path inside
    # artifacts/ collides under parallel runs and breaks on read-only
    # checkouts
    fresh = tmp_path / "lock_graph.fresh.json"
    write_lock_graph(Project.from_paths([PACKAGE_ROOT]), fresh)
    assert fresh.read_bytes() == artifact.read_bytes(), (
        "the checked-in lock graph no longer matches the tree "
        "(locks or acquisition orders changed): review the diff, "
        "then regenerate artifacts/lock_graph.json — the item-2 "
        "event-loop refactor certifies against this artifact")
    doc = json.loads(artifact.read_text("utf-8"))
    # the web the dispatchers run on is certified ACYCLIC by the
    # lock-order rule; a cycle here means the clean-run test above is
    # broken, not the code
    assert doc["locks"], "lock graph lost its lock table"


def test_event_loop_surface_artifact_matches_the_tree(tmp_path):
    from dat_replication_protocol_tpu.analysis.__main__ import \
        write_event_loop_surface

    artifact = REPO_ROOT / "artifacts" / "event_loop_surface.json"
    assert artifact.exists(), (
        "artifacts/event_loop_surface.json is missing — regenerate "
        "with python -m dat_replication_protocol_tpu.analysis "
        "--write-artifacts artifacts")
    fresh = tmp_path / "event_loop_surface.fresh.json"
    write_event_loop_surface(Project.from_paths([PACKAGE_ROOT]), fresh)
    assert fresh.read_bytes() == artifact.read_bytes(), (
        "the checked-in event-loop readiness certificate no longer "
        "matches the tree (a blocking site, callback edge, or entry "
        "point moved): review the diff, then regenerate with "
        "--write-artifacts artifacts — ROADMAP item 2 is a diff of "
        "this certificate")
    doc = json.loads(artifact.read_text("utf-8"))
    # a named entry point the analyzer cannot find anymore is a LOUD
    # hole, not a thinner certificate
    assert doc["missing_entry_points"] == [], (
        "entry points vanished from the certificate: "
        f"{doc['missing_entry_points']}")
    # the acceptance bar of ISSUE 16/17: every production dispatch loop
    # — hub, fanout, and the event-driven edge — certifies clean: each
    # reachable unbounded site and callback carries an audited allow
    # marker
    by_entry = {e["entry"]: e for e in doc["entry_points"]}
    for entry in ("hub-dispatch", "fanout-dispatch", "edge-dispatch"):
        e = by_entry[entry]
        assert e["enforced"] and e["certified"], (
            f"{entry} lost its readiness certification")
        assert e["classification"] != "unbounded-blocking"
    # the surfaces the item-2 rewrite must absorb are enumerated with
    # evidence, not empty: an empty enumeration means the analyzer
    # went blind, not that the code got clean overnight
    assert by_entry["sidecar-subscriber"]["unbounded"], (
        "sidecar-subscriber's remaining unbounded sites vanished — "
        "analyzer scope regression?")


def test_registry_ships_the_incident_rules():
    # the gate is only as strong as the registry: losing a rule from
    # ALL_RULES would turn the clean-run above into a weaker check
    # without any test failing
    assert {r.name for r in ALL_RULES} >= {
        "cursor-coherence",
        "env-cache-policy",
        "unbounded-join",
        "bounded-wait",
        "jit-purity",
        "wire-constant-parity",
        "wire-dispatch-parity",
        "obs-discipline",
        "lock-order",
        "blocking-under-lock",
        "guarded-state",
        "blocking-reachability",
        "callback-escape",
        "stale-suppression",
    }


def test_analyzer_actually_saw_the_protocol_stack():
    # guard against a silent scope regression (e.g. a _SKIP_DIRS typo
    # excluding session/): the decoder, both C sources, and the wire
    # layer must be in the analyzed file set
    from dat_replication_protocol_tpu.analysis.engine import Project

    project = Project.from_paths([PACKAGE_ROOT])
    names = {p.name for p in (s.path for s in project.sources)}
    assert {"decoder.py", "framing.py", "change_codec.py",
            "dat_native.cpp", "dat_fastpath.cpp"} <= names
