"""Mesh convergence plane (ISSUE 19): the propagation board's
divergence-watermark semantics, the dark-path bytecode contract on the
exchange engine, the lit sim's provenance records, and the offline
``obs meshdoctor`` — including the 20-seed chaos oracle that checks
the doctor's stalled-link attribution against the fault injector's
ground truth (the generator IS the oracle, tests never guess).
"""

import json

import pytest

from dat_replication_protocol_tpu.cluster import ClusterSim
from dat_replication_protocol_tpu.cluster import node as cluster_node
from dat_replication_protocol_tpu.obs import propagation
from dat_replication_protocol_tpu.obs.__main__ import (
    _dedupe_exchanges,
    _link_runs,
    _meshdoctor_analyze,
    main as obs_main,
)
from dat_replication_protocol_tpu.obs.events import EVENTS
from dat_replication_protocol_tpu.obs.metrics import REGISTRY
from dat_replication_protocol_tpu.obs.tracing import (
    SPANS,
    attach_jsonl_sink,
)
from dat_replication_protocol_tpu.session.faults import FaultPlan


# -- dark-path discipline (the PR 18 contract, at the bytecode level) --------


def test_dark_twin_references_no_propagation_symbol():
    """The dark `_exchange` twin must not mention the plane AT ALL:
    the disabled cost of the whole convergence plane is one attribute
    load in `gossip_exchange`, proven on the compiled code object, not
    by reading the source."""
    names = cluster_node._exchange.__code__.co_names
    assert not any("propagation" in n for n in names), names
    assert "record_exchange" not in names
    assert "note_frontier" not in names


def test_gossip_exchange_fork_is_one_attribute_load():
    names = cluster_node.gossip_exchange.__code__.co_names
    assert {"_OBS", "on", "_exchange", "_exchange_lit"} <= set(names)


def test_lit_twin_does_reference_the_plane():
    """The inverse direction: if a refactor quietly dropped the lit
    twin's instrumentation, the dark test above would still pass."""
    names = cluster_node._exchange_lit.__code__.co_names
    assert any("propagation" in n for n in names), names


def test_dark_run_leaves_board_and_rings_empty():
    assert not propagation.OBS.on, "dark test needs the gate off"
    propagation.PROPAGATION.reset_for_tests()
    EVENTS.clear()
    SPANS.clear()
    sim = ClusterSim(3, seed=5, records_per=4, divergence=2, chaos=False)
    assert sim.run()["converged"]
    snap = propagation.PROPAGATION.snapshot()
    assert snap["links"] == {}
    assert snap["frontier"] == {}
    assert snap["exchange_seconds"]["count"] == 0
    assert SPANS.spans("gossip.exchange") == []
    assert EVENTS.events("gossip.mesh") == []


# -- board unit semantics -----------------------------------------------------


def test_success_sets_watermark_failure_keeps_it():
    board = propagation.PropagationBoard()
    board.record("r0", "r1", role="initiator", rnd=1, outcome="progress",
                 seconds=0.01, diff=7, wire_bytes=900, repair_bytes=640)
    rec = board.snapshot()["links"]["r0->r1"]
    assert rec["divergence_records"] == 7
    assert rec["divergence_bytes"] == 640
    assert rec["failures"] == 0
    # a failed exchange did NOT heal the divergence: the watermark
    # stays (fabricating 0 would read as converged — the direction an
    # SLO gate must never err in), only the failure count moves
    board.record("r0", "r1", role="initiator", rnd=2, outcome="transport",
                 seconds=0.02, error="link cut")
    rec = board.snapshot()["links"]["r0->r1"]
    assert rec["divergence_records"] == 7
    assert rec["divergence_bytes"] == 640
    assert rec["failures"] == 1
    assert rec["outcome"] == "transport"
    assert rec["error"] == "link cut"
    assert rec["exchanges"] == 2
    # convergence zeroes it
    board.record("r0", "r1", role="initiator", rnd=3, outcome="converged",
                 seconds=0.01, diff=0)
    rec = board.snapshot()["links"]["r0->r1"]
    assert rec["divergence_records"] == 0
    assert rec["divergence_bytes"] == 0


def test_failure_before_any_peel_reports_unknown_not_zero():
    board = propagation.PropagationBoard()
    board.record("r0", "r1", role="initiator", rnd=1, outcome="transport",
                 seconds=0.0)
    rec = board.snapshot()["links"]["r0->r1"]
    assert rec["divergence_records"] is None
    assert rec["divergence_bytes"] is None
    assert rec["last_success_age_s"] is None
    # and the collector skips the link: unknown is not a gauge value
    assert board._collect()["gauges"] == {}


def test_refused_exchanges_stay_out_of_the_seconds_window():
    board = propagation.PropagationBoard()
    assert board.exchange_p99() is None
    board.record("r0", "r1", role="initiator", rnd=1, outcome="refused",
                 seconds=9.9, error="quarantined")
    assert board.exchange_p99() is None
    board.record("r0", "r1", role="initiator", rnd=2, outcome="progress",
                 seconds=0.25, diff=1)
    assert board.exchange_p99() == 0.25


def test_exchange_quantiles_over_known_window():
    board = propagation.PropagationBoard()
    for i in range(100):
        board.record("r0", "r1", role="initiator", rnd=i,
                     outcome="progress", seconds=(i + 1) / 100.0, diff=1)
    assert board._quantile(0.50) == pytest.approx(0.50)
    assert board.exchange_p99() == pytest.approx(0.99)
    xs = board.snapshot()["exchange_seconds"]
    assert xs["count"] == 100
    assert xs["p50"] == pytest.approx(0.50)
    assert xs["p99"] == pytest.approx(0.99)


def test_snapshot_ages_are_monotonic_clock_relative():
    board = propagation.PropagationBoard()
    board.record("r0", "r1", role="initiator", rnd=1, outcome="converged",
                 seconds=0.01, diff=0)
    rec = board.snapshot()["links"]["r0->r1"]
    assert rec["age_s"] >= 0.0
    assert rec["last_success_age_s"] >= 0.0
    assert rec["last_success_age_s"] <= rec["age_s"] + 0.001


def test_note_frontier_is_change_only():
    board = propagation.PropagationBoard()
    assert board.note_frontier("r0", "aa" * 16, 3, 0)
    assert not board.note_frontier("r0", "aa" * 16, 3, 1)
    assert board.note_frontier("r0", "bb" * 16, 4, 2)
    assert board.snapshot()["frontier"]["r0"] == {
        "digest": "bb" * 16, "records": 4, "round": 2}


def test_collector_exports_divergence_and_frontier_gauges():
    board = propagation.PropagationBoard()
    board.record("r0", "r1", role="initiator", rnd=1, outcome="progress",
                 seconds=0.01, diff=3, repair_bytes=300)
    board.note_frontier("r0", "ff" * 16, 5, 1)
    gauges = board._collect()["gauges"]
    assert gauges["cluster.divergence{replica=r0,peer=r1}"] == 3.0
    assert gauges["cluster.divergence_bytes{replica=r0,peer=r1}"] == 300.0
    assert gauges["cluster.frontier{replica=r0}"] == \
        propagation.frontier_fingerprint("ff" * 16)


def test_frontier_fingerprint_is_an_exact_equality_token():
    a = propagation.frontier_fingerprint("f" * 64)
    assert a == float(int("f" * 13, 16))
    assert a == propagation.frontier_fingerprint("f" * 13)
    assert a != propagation.frontier_fingerprint("e" + "f" * 12)
    # 52 bits: exactly representable, no rounding collisions
    assert float(int("f" * 13, 16)) != float(int("f" * 13, 16) - 1)


def test_digest_prefixes_hex16():
    rows = [bytes(range(32)), b"\xff" * 32]
    assert propagation.digest_prefixes(rows) == [
        bytes(range(32)).hex()[:16], "ff" * 8]


def test_reset_for_tests_drops_everything():
    board = propagation.PropagationBoard()
    board.record("r0", "r1", role="initiator", rnd=1, outcome="progress",
                 seconds=0.5, diff=1)
    board.note_frontier("r0", "aa" * 16, 1, 1)
    board.reset_for_tests()
    snap = board.snapshot()
    assert snap["links"] == {} and snap["frontier"] == {}
    assert board.exchange_p99() is None


# -- lit integration: the sim records provenance ------------------------------


def test_lit_sim_populates_board_spans_and_gauges(obs_enabled):
    sim = ClusterSim(4, seed=3, records_per=6, divergence=2, chaos=False)
    assert sim.run()["converged"]
    snap = propagation.PROPAGATION.snapshot()
    assert snap["links"], "lit exchanges must leave link watermarks"
    digests = {rec["digest"] for rec in snap["frontier"].values()}
    assert len(snap["frontier"]) == 4
    assert len(digests) == 1, "converged mesh: one frontier digest"
    assert snap["exchange_seconds"]["p99"] is not None
    spans = SPANS.spans("gossip.exchange")
    assert spans
    for r in spans:
        f = r["fields"]
        assert f["outcome"] in propagation.OUTCOMES
        assert f["role"] in ("initiator", "responder")
        assert {"replica", "peer", "round", "seconds",
                "wire_bytes"} <= set(f)
    # both directions of each in-process exchange are recorded
    roles = {r["fields"]["role"] for r in spans}
    assert roles == {"initiator", "responder"}
    # the registry exports the matrix through the collector
    gauges = REGISTRY.snapshot()["gauges"]
    frontier_g = {k: v for k, v in gauges.items()
                  if k.startswith("cluster.frontier{")}
    assert len(frontier_g) == 4
    assert len(set(frontier_g.values())) == 1
    assert any(k.startswith("cluster.divergence{") for k in gauges)
    mesh_ev = EVENTS.events("gossip.mesh")
    assert len(mesh_ev) == 1
    assert mesh_ev[0]["fields"] == {"n": 4, "seed": 3,
                                    "bound": sim.rounds_bound()}
    # provenance roots: one hold per replica at round 0
    holds = EVENTS.events("gossip.hold")
    assert {h["fields"]["replica"] for h in holds} == set(sim.nodes)


# -- meshdoctor: offline attribution ------------------------------------------


def _run_lit_sim(seed, *, chaos, n=4):
    propagation.PROPAGATION.reset_for_tests()
    EVENTS.clear()
    SPANS.clear()
    sim = ClusterSim(n, seed, records_per=6, divergence=2, chaos=chaos)
    out = sim.run()
    return sim, out, EVENTS.events(), SPANS.spans()


def test_meshdoctor_clean_seed_exits_zero(obs_enabled, tmp_path, capsys):
    log = tmp_path / "mesh.jsonl"
    sink = attach_jsonl_sink(str(log))
    try:
        sim, out, _ev, _sp = _run_lit_sim(3, chaos=False)
    finally:
        EVENTS.attach_sink(None)
        SPANS.attach_sink(None)
        sink.close()
    assert out["converged"]
    assert obs_main(["meshdoctor", str(log)]) == 0
    text = capsys.readouterr().out
    assert "final divergence exactly 0" in text
    assert "FLAG" not in text
    assert "slowest: digest" in text
    assert obs_main(["meshdoctor", "--json", str(log)]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["converged"] and rep["flags"] == []
    assert rep["distinct_frontiers"] == 1
    assert rep["convergence_round"] <= rep["bound"] == sim.rounds_bound()
    assert rep["mesh"]["n"] == 4 and rep["mesh"]["seed"] == 3
    assert rep["tree_digests"] > 0


def _predicted_stalls(sim):
    """Ground truth straight from the sim's event log: undirected
    pairs that failed transport in >= 2 DISTINCT rounds with no
    successful exchange in between — the same rule the doctor applies
    to its reconstructed spans, computed from the injector side."""
    by_pair: dict = {}
    for ev in sim.events:
        for x in ev["exchanges"]:
            if x["outcome"] not in ("ok", "transport"):
                continue
            pair = tuple(sorted((x["initiator"], x["responder"])))
            by_pair.setdefault(pair, []).append(
                (ev["round"], x["outcome"] == "ok"))
    stalled = set()
    for pair, obs in by_pair.items():
        obs.sort()
        if any(len(run) >= 2 for run in _link_runs(obs)):
            stalled.add(pair)
    return stalled


def test_meshdoctor_chaos_oracle_20_seeds(obs_enabled):
    """The acceptance oracle: 20 chaos seeds, every stalled-link flag
    the doctor raises must name EXACTLY the links the fault injector's
    own event log predicts, every flagged link must cross the
    partition cut, every flagged round must fall inside
    [cut_round, heal_round), and clean/healed seeds must converge with
    final divergence exactly 0 within rounds_bound()."""
    total_flags = 0
    for seed in range(20):
        sim, out, events, spans = _run_lit_sim(seed, chaos=True)
        rep = _meshdoctor_analyze(events, spans)
        stalls = {tuple(sorted(f["link"].split("<->")))
                  for f in rep["flags"] if f["flag"] == "stalled-link"}
        assert stalls == _predicted_stalls(sim), f"seed {seed}"
        # only the partition produces repeat offenders: one-shot link
        # chaos fires at most one round per link
        other = [f["flag"] for f in rep["flags"]
                 if f["flag"] != "stalled-link"]
        assert other == [], f"seed {seed}: unexpected flags {other}"
        sc = FaultPlan.partition_scenario(seed, 4)
        minority = sc["groups"][0]
        for f in rep["flags"]:
            a, b = f["link"].split("<->")
            assert (int(a[1:]) in minority) != (int(b[1:]) in minority), \
                f"seed {seed}: {f['link']} does not cross the cut"
            assert all(sc["cut_round"] <= r < sc["heal_round"]
                       for r in f["rounds"]), f"seed {seed}: {f}"
        # the mesh HEALS: convergence within the budget, divergence 0
        assert out["converged"], f"seed {seed} never converged"
        assert rep["converged"], f"seed {seed}"
        assert rep["distinct_frontiers"] == 1, f"seed {seed}"
        assert rep["convergence_round"] <= sim.rounds_bound(), \
            f"seed {seed}"
        total_flags += len(stalls)
    assert total_flags > 0, \
        "vacuous oracle: no seed produced a stalled link"


def _span(rnd, replica, peer, role, outcome, ts, **fields):
    f = {"replica": replica, "peer": peer, "role": role, "round": rnd,
         "outcome": outcome, "wire_bytes": 0, "repair_bytes": 0,
         "seconds": 0.001, **fields}
    return {"seq": 0, "ts": ts, "dur": 0.001, "span": "gossip.exchange",
            "id": int(ts * 1000), "parent": None, "tid": 0, "fields": f}


def test_meshdoctor_flags_asymmetric_link():
    """One direction fails 2 distinct rounds while the reverse
    succeeds inside the same span: a half-open link, not a
    partition."""
    spans = [
        _span(1, "r0", "r1", "initiator", "transport", 1.0),
        _span(1, "r1", "r0", "initiator", "progress", 1.1, diff=1),
        _span(2, "r0", "r1", "initiator", "transport", 2.0),
    ]
    rep = _meshdoctor_analyze([], spans)
    kinds = {f["flag"] for f in rep["flags"]}
    assert "asymmetric-link" in kinds
    (fl,) = [f for f in rep["flags"] if f["flag"] == "asymmetric-link"]
    assert fl["link"] == "r0->r1"
    assert fl["rounds"] == [1, 2]
    # NOT a stalled pair: the undirected view saw a success at round 1
    assert "stalled-link" not in kinds


def test_meshdoctor_flags_orphaned_digest():
    """An exchange delivered a digest its sender was never recorded
    holding: a provenance break, only checkable when hold records
    exist (bare live logs without roots are not accused)."""
    holds = [
        {"seq": 0, "ts": 0.0, "event": "gossip.hold",
         "fields": {"replica": "r0", "round": 0, "digests": ["aa" * 8]}},
        {"seq": 1, "ts": 0.0, "event": "gossip.hold",
         "fields": {"replica": "r1", "round": 0, "digests": ["bb" * 8]}},
    ]
    spans = [_span(1, "r0", "r1", "initiator", "progress", 1.0,
                   diff=1, delivered=["cc" * 8])]
    rep = _meshdoctor_analyze(holds, spans)
    (fl,) = [f for f in rep["flags"] if f["flag"] == "orphaned-digest"]
    assert fl["digest"] == "cc" * 8
    assert fl["link"] == "r1->r0"
    # without the hold roots the same spans pass clean
    rep2 = _meshdoctor_analyze([], spans)
    assert not [f for f in rep2["flags"]
                if f["flag"] == "orphaned-digest"]


def test_meshdoctor_flags_rounds_bound_exceeded():
    mesh = {"seq": 0, "ts": 0.0, "event": "gossip.mesh",
            "fields": {"n": 2, "seed": 0, "bound": 3}}
    frontiers = [
        {"seq": 1, "ts": 0.1, "event": "gossip.frontier",
         "fields": {"replica": "r0", "round": 5, "digest": "aa" * 16,
                    "records": 3}},
        {"seq": 2, "ts": 0.2, "event": "gossip.frontier",
         "fields": {"replica": "r1", "round": 5, "digest": "bb" * 16,
                    "records": 2}},
    ]
    spans = [_span(5, "r0", "r1", "initiator", "progress", 5.0, diff=1)]
    rep = _meshdoctor_analyze([mesh] + frontiers, spans)
    assert not rep["converged"] and rep["distinct_frontiers"] == 2
    (fl,) = [f for f in rep["flags"]
             if f["flag"] == "rounds-bound-exceeded"]
    assert "never converged" in fl["detail"]
    # the converged-but-late arm
    late = [dict(f, fields=dict(f["fields"], digest="aa" * 16))
            for f in frontiers]
    rep2 = _meshdoctor_analyze([mesh] + late, spans)
    (fl2,) = [f for f in rep2["flags"]
              if f["flag"] == "rounds-bound-exceeded"]
    assert "converged at round 5" in fl2["detail"]


def test_meshdoctor_exit_codes_and_graceful_empty(tmp_path, capsys):
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert obs_main(["meshdoctor", str(empty)]) == 0
    assert "never ran lit" in capsys.readouterr().out
    # a flagged log exits 1 (the CI-gate contract)
    bad = tmp_path / "bad.jsonl"
    with open(bad, "w") as f:
        for rec in (_span(1, "r0", "r1", "initiator", "transport", 1.0),
                    _span(2, "r0", "r1", "initiator", "transport", 2.0)):
            f.write(json.dumps(rec) + "\n")
    assert obs_main(["meshdoctor", str(bad)]) == 1
    assert "FLAG stalled-link" in capsys.readouterr().out


def test_dedupe_prefers_the_initiator_view():
    spans = [
        _span(1, "r1", "r0", "responder", "progress", 1.0,
              diff=2, delivered=["aa" * 8], delivered_peer=["bb" * 8]),
        _span(1, "r0", "r1", "initiator", "progress", 1.1,
              diff=2, delivered=["bb" * 8], delivered_peer=["aa" * 8]),
    ]
    (x,) = _dedupe_exchanges(spans)
    assert (x["dialer"], x["dialee"]) == ("r0", "r1")
    assert x["delivered_dialer"] == ["bb" * 8]
    assert x["delivered_dialee"] == ["aa" * 8]


def test_link_runs_gaps_do_not_break_a_stall():
    # rounds 2 and 5 failed, nothing observed between: one run — a
    # partitioned pair is only sampled some rounds
    assert _link_runs([(2, False), (5, False)]) == [[2, 5]]
    # a success between failures splits the runs
    assert _link_runs([(2, False), (3, True), (5, False)]) == [[2], [5]]
    # duplicate failures in one round count once
    assert _link_runs([(2, False), (2, False)]) == [[2]]
