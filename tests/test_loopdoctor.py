"""``obs loopdoctor`` (ISSUE 18): offline stall attribution, verified
by a 20-seed chaos oracle over the live edge loop.

The oracle: for each FaultPlan seed the sweep either injects a
server-side read stall into the FaultPlan-elected session (the plan's
``stall`` scenario) or runs fully clean.  The doctor, fed nothing but
the ``edge.turn`` span JSONL the profiler wrote, must

* on stall seeds — exit 1 with a ``stall-dominance`` flag naming the
  faulted session AND the ``read`` phase, carrying at least the
  injected stall duration;
* on clean seeds — exit 0 with ZERO flags and a final lag of exactly
  0.0 (the lag formula clamps clean turns to zero, not epsilon).

A live ``/healthz`` integration run proves the loop-lag stage flips
degraded DURING the stall and recovers after it, and CLI-level runs
prove the exit codes end-to-end.
"""

import argparse
import json
import socket
import subprocess
import sys
import threading
import time

import pytest

from dat_replication_protocol_tpu.edge import EdgeLoop
from dat_replication_protocol_tpu.hub import ReplicationHub
from dat_replication_protocol_tpu.obs.__main__ import (
    _loopdoctor_analyze,
    cmd_loopdoctor,
)
from dat_replication_protocol_tpu.obs.tracing import SPANS
from dat_replication_protocol_tpu.session.faults import FaultPlan

from test_wire_fixtures import SESSION_4

N_SESSIONS = 4
SEEDS = range(20)
TICK = 0.05
STALL_S = 0.35
# explicit doctor threshold: far above any clean turn's work, well
# under the injected stall
THRESHOLD_S = 0.15


def _recv_all(sock: socket.socket) -> bytes:
    parts = []
    while True:
        try:
            d = sock.recv(65536)
        except OSError:
            return b"".join(parts)
        if not d:
            return b"".join(parts)
        parts.append(d)


def _client(addr):
    c = socket.create_connection(addr, timeout=10)
    c.settimeout(20)
    c.sendall(SESSION_4)
    c.shutdown(socket.SHUT_WR)
    assert _recv_all(c)
    c.close()


@pytest.fixture(scope="module", autouse=True)
def _warmup():
    """One full session before the sweep: first-run compile/init costs
    must not read as loop lag in the clean-seed oracle."""
    hub = ReplicationHub(linger_s=0.002)
    loop = EdgeLoop(hub, max_sessions=1, tick=TICK)
    port = loop.bind("127.0.0.1", 0)
    t = threading.Thread(target=loop.serve, daemon=True)
    t.start()
    try:
        _client(("127.0.0.1", port))
        t.join(timeout=15)
    finally:
        loop.close()
        hub.close()


def _stalling_read(faulty_key_prefix: str, fired: dict):
    """An EdgeLoop._read_turn wrapper that parks the loop inside the
    elected session's first read turn — the injected FaultPlan stall,
    server-side, inside the phase-accounting window."""
    orig = EdgeLoop._read_turn

    def read_turn(self, sess, now):
        if not fired.get("done") and sess.key.startswith(
                faulty_key_prefix):
            fired["done"] = True
            time.sleep(STALL_S)
        return orig(self, sess, now)

    return read_turn


def _run_sweep(monkeypatch, stall_session=None) -> tuple:
    """N staggered sessions through one lit loop; returns (loop_name,
    spans).  ``stall_session`` (0-based index) injects the read
    stall into that session's turn."""
    hub = ReplicationHub(linger_s=0.002)
    loop = EdgeLoop(hub, max_sessions=N_SESSIONS, tick=TICK)
    fired: dict = {}
    if stall_session is not None:
        # admission order is the 0.02s stagger below: session i is
        # connection n=i+1, key c{n}:host:port
        monkeypatch.setattr(
            EdgeLoop, "_read_turn",
            _stalling_read(f"c{stall_session + 1}:", fired))
    port = loop.bind("127.0.0.1", 0)
    t = threading.Thread(target=loop.serve, daemon=True)
    t.start()
    try:
        addr = ("127.0.0.1", port)
        threads = []
        for _ in range(N_SESSIONS):
            th = threading.Thread(target=_client, args=(addr,),
                                  daemon=True)
            threads.append(th)
            th.start()
            time.sleep(0.02)  # deterministic admission order
        for th in threads:
            th.join(20)
            assert not th.is_alive(), "client HANG"
        t.join(timeout=15)
        assert not t.is_alive(), "loop HANG"
    finally:
        loop.close()
        hub.close()
    if stall_session is not None:
        assert fired.get("done"), "stall was never injected"
    name = loop.profiler.name
    spans = [r for r in SPANS.spans("edge.turn")
             if r["fields"]["loop"] == name]
    return name, spans


def _write_jsonl(tmp_path, spans) -> str:
    path = tmp_path / "spans.jsonl"
    with open(path, "w") as f:
        for r in spans:
            f.write(json.dumps(r) + "\n")
    return str(path)


def _doctor(log: str, json_out=False) -> tuple:
    args = argparse.Namespace(log=log, threshold=THRESHOLD_S,
                              json=json_out)
    return cmd_loopdoctor(args)


# -- the 20-seed oracle ------------------------------------------------------

def test_oracle_covers_both_arms():
    scenarios = {FaultPlan.session_scenario(s, N_SESSIONS)
                 for s in SEEDS}
    assert "stall" in scenarios and len(scenarios) > 1


@pytest.mark.parametrize("seed", SEEDS)
def test_loopdoctor_oracle(seed, obs_enabled, monkeypatch, tmp_path,
                           capsys):
    faulty = FaultPlan.faulty_session(seed, N_SESSIONS)
    scenario = FaultPlan.session_scenario(seed, N_SESSIONS)
    stall = faulty if scenario == "stall" else None
    name, spans = _run_sweep(monkeypatch, stall_session=stall)
    assert spans, f"seed {seed}: no edge.turn spans recorded"
    log = _write_jsonl(tmp_path, spans)
    rc = _doctor(log)
    out = capsys.readouterr().out
    report = _loopdoctor_analyze(spans, threshold=THRESHOLD_S)
    rec = report["loops"][name]
    if scenario == "stall":
        # the doctor names the faulted session, the read phase, and at
        # least the injected stall duration — and exits 1
        assert rc == 1, f"seed {seed}: doctored run passed clean"
        dom = [fl for fl in report["flags"]
               if fl["flag"] == "stall-dominance"]
        assert dom, f"seed {seed}: no stall-dominance flag"
        fl = dom[0]
        assert fl["session"].startswith(f"c{faulty + 1}:"), (
            f"seed {seed}: stall attributed to {fl['session']}, "
            f"expected session c{faulty + 1}")
        assert fl["phase"] == "read"
        assert fl["seconds"] >= STALL_S
        assert fl["session"] in out and "stall-dominance" in out
        assert rec["lag_max_s"] >= STALL_S - TICK
    else:
        # clean seed: zero flags, exit 0, lag lands at EXACTLY zero
        assert rc == 0, (
            f"seed {seed} ({scenario}): clean run flagged: "
            f"{report['flags']}")
        assert report["flags"] == []
        assert rec["final_lag_s"] == 0.0
        assert "-- clean" in out


# -- /healthz flips degraded during the stall and recovers -------------------

def test_healthz_degrades_during_live_stall_and_recovers(
        obs_enabled, monkeypatch):
    from dat_replication_protocol_tpu.obs.http import default_healthz

    hub = ReplicationHub(linger_s=0.002)
    loop = EdgeLoop(hub, max_sessions=1, tick=TICK)
    monkeypatch.setattr(EdgeLoop, "_read_turn",
                        _stalling_read("c1:", {}))
    port = loop.bind("127.0.0.1", 0)
    t = threading.Thread(target=loop.serve, daemon=True)
    t.start()
    saw_degraded = False
    try:
        th = threading.Thread(target=_client,
                              args=(("127.0.0.1", port),), daemon=True)
        th.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            hz = default_healthz()
            stage = hz["stages"].get("loop_lag")
            if stage is not None and not stage["ok"]:
                assert loop.profiler.name in stage["behind"]
                assert not hz["ok"]
                saw_degraded = True
                break
            time.sleep(0.01)
        th.join(20)
        t.join(timeout=15)
        assert not t.is_alive()
    finally:
        loop.close()
        hub.close()
    assert saw_degraded, "/healthz never saw the stall"
    # recovered: the loop detached at shutdown — no loops report, and
    # a fresh clean loop reports ok
    hz = default_healthz()
    assert hz["stages"].get("loop_lag", {"ok": True})["ok"] is True


# -- CLI end-to-end (exit codes through the real entrypoint) -----------------

def _synthetic_spans(loop="edge-cli", stall=False) -> list:
    """Hand-built tiling edge.turn spans: three clean turns, optionally
    one stalled turn attributed to c2."""
    base = 1000.0
    spans = []
    ts = base
    turns = [(0.05, 0.001, None), (0.05, 0.002, None)]
    if stall:
        turns.append((0.001, 0.4, ("c2:127.0.0.1:5", 0.4, "read")))
    turns.append((0.05, 0.001, None))
    for poll, work, top in turns:
        fields = {"loop": loop, "tick": 0.05, "turns": 1, "sessions": 1,
                  "poll_wait_s": poll, "work_s": work,
                  "lag_s": max(0.0, work - 0.05), "accept_s": 0.0,
                  "read_s": work, "hub_drain_s": 0.0, "tx_s": 0.0,
                  "overload_ladder_s": 0.0}
        if top is not None:
            key, sec, phase = top
            fields["top"] = [{"session": key, "seconds": sec,
                              "bytes": 512, "phase": phase}]
        dur = poll + work
        spans.append({"seq": 0, "ts": ts, "dur": dur, "span": "edge.turn",
                      "id": len(spans) + 1, "parent": None, "tid": 1,
                      "fields": fields})
        ts += dur
    return spans


@pytest.mark.parametrize("stall,expect_rc", [(False, 0), (True, 1)])
def test_loopdoctor_cli_exit_codes(tmp_path, stall, expect_rc):
    path = tmp_path / "log.jsonl"
    with open(path, "w") as f:
        for r in _synthetic_spans(stall=stall):
            f.write(json.dumps(r) + "\n")
    proc = subprocess.run(
        [sys.executable, "-m", "dat_replication_protocol_tpu.obs",
         "loopdoctor", str(path), "--threshold", str(THRESHOLD_S)],
        capture_output=True, text=True, timeout=120,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == expect_rc, proc.stdout + proc.stderr
    if stall:
        assert "c2:127.0.0.1:5" in proc.stdout
        assert "stall-dominance" in proc.stdout
    else:
        assert "-- clean" in proc.stdout


def test_loopdoctor_flags_broken_tiling():
    spans = _synthetic_spans()
    spans[2]["ts"] += 0.5  # tear a hole in the tiling
    report = _loopdoctor_analyze(spans)
    assert [fl["flag"] for fl in report["flags"]] == ["tile-gap"]
    spans = _synthetic_spans()
    spans[2]["ts"] -= 0.01
    report = _loopdoctor_analyze(spans)
    assert [fl["flag"] for fl in report["flags"]] == ["tile-overlap"]


def test_loopdoctor_flags_unattributed_stall():
    spans = _synthetic_spans(stall=True)
    for r in spans:
        r["fields"].pop("top", None)
    report = _loopdoctor_analyze(spans, threshold=THRESHOLD_S)
    assert any(fl["flag"] == "unattributed-stall"
               for fl in report["flags"])


def test_loopdoctor_empty_log_is_clean(tmp_path, capsys):
    path = tmp_path / "empty.jsonl"
    path.write_text("")
    assert _doctor(str(path)) == 0
    assert "no edge.turn spans" in capsys.readouterr().out
