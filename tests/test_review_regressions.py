"""Regression tests for defects found in review: parser reentrancy, parked
backpressure accounting, str writes, Pipe.done liveness, required-field
enforcement, destroy notification, and the backend='tpu' entry point."""

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.wire.change_codec import Change, encode_change


def test_parser_reentrancy_synchronous_done_across_parked_chunks():
    """A handler acking synchronously while parsing resumes mid-chunk must not
    reorder parked chunks (reentrancy into _consume)."""
    e = protocol.encode()
    for i in range(4):
        e.change({"key": f"k{i}", "change": i, "from": 0, "to": 1, "value": b"x" * 40})
    e.finalize()
    wire = bytearray()
    while (c := e.read()) not in (None, b""):
        wire += c

    d = protocol.decode()
    got = []
    held = []

    def on_change(c, done):
        got.append(c.key)
        if c.key == "k0":
            held.append(done)  # defer only the first; rest ack synchronously
        else:
            done()

    d.change(on_change)
    # split so frame boundaries straddle the parked chunks
    third = len(wire) // 3
    d.write(wire[:third])
    d.write(wire[third : 2 * third])
    d.write(wire[2 * third :])
    assert got == ["k0"]
    held.pop()()  # releasing must parse the remaining frames in order
    d.end()
    assert got == ["k0", "k1", "k2", "k3"]
    assert d.finished and not d.destroyed


def test_parked_blob_bytes_count_toward_high_water():
    e = protocol.encode(high_water=64)
    e.blob(1000)  # head blob, streams slowly
    b2 = e.blob(100)
    assert b2.write(b"x" * 100) is False  # parked bytes must apply backpressure
    assert e.buffered_bytes + e._parked_bytes >= 64


def test_parked_change_bytes_count_toward_high_water():
    e = protocol.encode(high_water=64)
    e.blob(1000)
    ok = e.change({"key": "k" * 100, "change": 1, "from": 0, "to": 1})
    assert ok is False


def test_str_writes_accepted_everywhere():
    e = protocol.encode()
    d = protocol.decode()
    got = []
    d.blob(lambda blob, done: blob.collect(lambda x: (got.append(x), done())))
    b = e.blob(11)
    b.write("hello ")
    b.end("world")
    e.finalize()
    protocol.pipe(e, d)
    assert got == [b"hello world"]
    # decoder str input
    d2 = protocol.decode()
    assert d2.write("") is True


def test_pipe_done_reflects_late_finalize_ack():
    e = protocol.encode()
    d = protocol.decode()
    fin = []
    d.finalize(lambda done: fin.append(done))
    e.change({"key": "k", "change": 1, "from": 0, "to": 1})
    e.finalize()
    p = protocol.pipe(e, d)
    assert p.done is False
    fin.pop()()
    assert d.finished and p.done is True


def test_from_dict_missing_from_raises():
    with pytest.raises(KeyError):
        encode_change({"key": "k", "change": 1, "to": 5})


def test_destroy_releases_parked_write_callbacks():
    e = protocol.encode()
    e.change({"key": "k", "change": 1, "from": 0, "to": 1, "value": b"v"})
    e.change({"key": "bad", "change": 2, "from": 0, "to": 1})
    e.finalize()
    wire = bytearray()
    while (c := e.read()) not in (None, b""):
        wire += c
    wire += bytes(protocol.wire.frame(9, b"zz"))  # trailing garbage frame

    d = protocol.decode()
    held = []
    woke = []
    d.change(lambda c, done: held.append(done))
    d.on_error(lambda err: None)
    d.write(bytes(wire), on_consumed=lambda: woke.append("consumed"))
    assert woke == []  # stalled on held done
    held.pop()()  # resumes parsing; second change stalls again
    held.pop()()  # resumes; garbage frame destroys the session
    assert d.destroyed
    assert woke == ["consumed"]  # parked write cb released on destroy


def test_tpu_backend_entry_points_work():
    e = protocol.encode(backend="tpu")
    d = protocol.decode(backend="tpu")
    digests = []
    d.on_digest(lambda kind, seq, dg: digests.append((kind, seq, dg)))
    order = []
    d.change(lambda c, done: (order.append("change"), done()))
    d.blob(lambda blob, done: blob.collect(lambda x: (order.append("blob"), done())))
    d.finalize(lambda done: (order.append("finalize"), done()))

    b = e.blob(11)
    b.write(b"hello ")
    b.end(b"world")
    e.change({"key": "k", "change": 1, "from": 0, "to": 1, "value": b"v"})
    e.finalize()
    protocol.pipe(e, d)

    assert d.finished
    # flush-before-finalize: digests delivered before the finalize hook
    assert order == ["blob", "change", "finalize"]
    import hashlib

    expect_blob = hashlib.blake2b(b"hello world", digest_size=32).digest()
    by_kind = {(k, s): dg for k, s, dg in digests}
    assert by_kind[("blob", 0)] == expect_blob
    assert ("change", 0) in by_kind


def test_tpu_encoder_digests_match_decoder():
    e = protocol.encode(backend="tpu")
    enc_digests = []
    e.on_digest(lambda kind, seq, dg: enc_digests.append((kind, seq, dg)))
    b = e.blob(5)
    b.end(b"12345")
    e.change({"key": "k", "change": 1, "from": 0, "to": 1})
    e.finalize()

    d = protocol.decode(backend="tpu")
    dec_digests = []
    d.on_digest(lambda kind, seq, dg: dec_digests.append((kind, seq, dg)))
    protocol.pipe(e, d)
    assert sorted(enc_digests) == sorted(dec_digests)
