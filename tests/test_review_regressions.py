"""Regression tests for defects found in review: parser reentrancy, parked
backpressure accounting, str writes, Pipe.done liveness, required-field
enforcement, destroy notification, and the backend='tpu' entry point."""

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.wire.change_codec import Change, encode_change


def test_parser_reentrancy_synchronous_done_across_parked_chunks():
    """A handler acking synchronously while parsing resumes mid-chunk must not
    reorder parked chunks (reentrancy into _consume)."""
    e = protocol.encode()
    for i in range(4):
        e.change({"key": f"k{i}", "change": i, "from": 0, "to": 1, "value": b"x" * 40})
    e.finalize()
    wire = bytearray()
    while (c := e.read()) not in (None, b""):
        wire += c

    d = protocol.decode()
    got = []
    held = []

    def on_change(c, done):
        got.append(c.key)
        if c.key == "k0":
            held.append(done)  # defer only the first; rest ack synchronously
        else:
            done()

    d.change(on_change)
    # split so frame boundaries straddle the parked chunks
    third = len(wire) // 3
    d.write(wire[:third])
    d.write(wire[third : 2 * third])
    d.write(wire[2 * third :])
    assert got == ["k0"]
    held.pop()()  # releasing must parse the remaining frames in order
    d.end()
    assert got == ["k0", "k1", "k2", "k3"]
    assert d.finished and not d.destroyed


def test_parked_blob_bytes_count_toward_high_water():
    e = protocol.encode(high_water=64)
    e.blob(1000)  # head blob, streams slowly
    b2 = e.blob(100)
    assert b2.write(b"x" * 100) is False  # parked bytes must apply backpressure
    assert e.buffered_bytes + e._parked_bytes >= 64


def test_parked_change_bytes_count_toward_high_water():
    e = protocol.encode(high_water=64)
    e.blob(1000)
    ok = e.change({"key": "k" * 100, "change": 1, "from": 0, "to": 1})
    assert ok is False


def test_str_writes_accepted_everywhere():
    e = protocol.encode()
    d = protocol.decode()
    got = []
    d.blob(lambda blob, done: blob.collect(lambda x: (got.append(x), done())))
    b = e.blob(11)
    b.write("hello ")
    b.end("world")
    e.finalize()
    protocol.pipe(e, d)
    assert got == [b"hello world"]
    # decoder str input
    d2 = protocol.decode()
    assert d2.write("") is True


def test_pipe_done_reflects_late_finalize_ack():
    e = protocol.encode()
    d = protocol.decode()
    fin = []
    d.finalize(lambda done: fin.append(done))
    e.change({"key": "k", "change": 1, "from": 0, "to": 1})
    e.finalize()
    p = protocol.pipe(e, d)
    assert p.done is False
    fin.pop()()
    assert d.finished and p.done is True


def test_from_dict_missing_from_raises():
    with pytest.raises(KeyError):
        encode_change({"key": "k", "change": 1, "to": 5})


def test_destroy_releases_parked_write_callbacks():
    e = protocol.encode()
    e.change({"key": "k", "change": 1, "from": 0, "to": 1, "value": b"v"})
    e.change({"key": "bad", "change": 2, "from": 0, "to": 1})
    e.finalize()
    wire = bytearray()
    while (c := e.read()) not in (None, b""):
        wire += c
    wire += bytes(protocol.wire.frame(9, b"zz"))  # trailing garbage frame

    d = protocol.decode()
    held = []
    woke = []
    d.change(lambda c, done: held.append(done))
    d.on_error(lambda err: None)
    d.write(bytes(wire), on_consumed=lambda: woke.append("consumed"))
    assert woke == []  # stalled on held done
    held.pop()()  # resumes parsing; second change stalls again
    held.pop()()  # resumes; garbage frame destroys the session
    assert d.destroyed
    assert woke == ["consumed"]  # parked write cb released on destroy


def test_tpu_backend_entry_points_work():
    e = protocol.encode(backend="tpu")
    d = protocol.decode(backend="tpu")
    digests = []
    d.on_digest(lambda kind, seq, dg: digests.append((kind, seq, dg)))
    order = []
    d.change(lambda c, done: (order.append("change"), done()))
    d.blob(lambda blob, done: blob.collect(lambda x: (order.append("blob"), done())))
    d.finalize(lambda done: (order.append("finalize"), done()))

    b = e.blob(11)
    b.write(b"hello ")
    b.end(b"world")
    e.change({"key": "k", "change": 1, "from": 0, "to": 1, "value": b"v"})
    e.finalize()
    protocol.pipe(e, d)

    assert d.finished
    # flush-before-finalize: digests delivered before the finalize hook
    assert order == ["blob", "change", "finalize"]
    import hashlib

    expect_blob = hashlib.blake2b(b"hello world", digest_size=32).digest()
    by_kind = {(k, s): dg for k, s, dg in digests}
    assert by_kind[("blob", 0)] == expect_blob
    assert ("change", 0) in by_kind


def test_tpu_encoder_digests_match_decoder():
    e = protocol.encode(backend="tpu")
    enc_digests = []
    e.on_digest(lambda kind, seq, dg: enc_digests.append((kind, seq, dg)))
    b = e.blob(5)
    b.end(b"12345")
    e.change({"key": "k", "change": 1, "from": 0, "to": 1})
    e.finalize()

    d = protocol.decode(backend="tpu")
    dec_digests = []
    d.on_digest(lambda kind, seq, dg: dec_digests.append((kind, seq, dg)))
    protocol.pipe(e, d)
    assert sorted(enc_digests) == sorted(dec_digests)


def _wire_of(build):
    e = protocol.encode()
    build(e)
    e.finalize()
    wire = bytearray()
    while (c := e.read()) not in (None, b""):
        wire += c
    return bytes(wire)


def test_deferred_done_does_not_finalize_past_unparsed_remainder():
    """Review: releasing a deferred done() while the outer _consume loop holds
    a chunk remainder in a local must not run finalize/finish before all
    frames are consumed, nor deliver frames after finished=True."""
    wire = _wire_of(
        lambda e: [
            e.change({"key": f"k{i}", "change": i, "from": 0, "to": 1})
            for i in range(3)
        ]
    )
    d = protocol.decode()
    events = []
    held = []

    def on_change(c, done):
        events.append(("change", c.key))
        if c.key == "k0":
            held.append(done)
        else:
            done()

    d.change(on_change)
    d.finalize(lambda done: (events.append(("finalize",)), done()))
    d.on_finish(lambda: events.append(("finish",)))
    # one write containing all three frames, then end
    d.write(wire)
    d.end()
    assert events == [("change", "k0")]
    held[0]()
    assert events == [
        ("change", "k0"),
        ("change", "k1"),
        ("change", "k2"),
        ("finalize",),
        ("finish",),
    ], events
    assert d.finished


def test_tpu_blob_double_end_single_digest():
    """Review: double end() on a tpu-backend blob writer must not duplicate
    the digest."""
    enc = protocol.encode(backend="tpu")
    digests = []
    enc.on_digest(lambda k, s, d: digests.append((k, s)))
    ws = enc.blob(3)
    ws.write(b"abc")
    ws.end()
    ws.end()
    enc.finalize()
    assert digests == [("blob", 0)]


def test_encoder_destroy_releases_drain_callbacks():
    """Review: a producer parked on on_drain must wake on destroy instead of
    hanging forever (mirrors decoder releasing parked write callbacks)."""
    e = protocol.encode(high_water=8)
    ws = e.blob(100)
    ws.write(b"x" * 50)  # above high water
    fired = []
    e.on_drain(lambda: fired.append(True))
    assert fired == []
    e.destroy(RuntimeError("boom"))
    assert fired == [True]


def test_truncated_fixed_width_fields_raise():
    """Review: a Change payload truncated mid fixed32/fixed64 unknown field
    must raise like every other truncation path."""
    from dat_replication_protocol_tpu.wire.change_codec import decode_change

    base = encode_change({"key": "k", "change": 1, "from": 0, "to": 1})
    for wire_type, nbytes in ((1, 8), (5, 4)):
        bad = base + bytes([(7 << 3) | wire_type]) + b"\x00\x00"  # 2 of n bytes
        with pytest.raises(ValueError):
            decode_change(bad)
        ok = base + bytes([(7 << 3) | wire_type]) + b"\x00" * nbytes
        decode_change(ok)  # fully-present unknown field still skips cleanly

# -- round-4 lifecycle / advisor fixes ---------------------------------------


def test_encoder_on_finish_after_finalize_and_drain():
    """The encoder-side 'close' (reference: encode.js) fires once the
    finalized session has fully drained — not before."""
    e = protocol.encode()
    seen = []
    e.on_finish(lambda: seen.append("finish"))
    e.change({"key": "k", "change": 1, "from": 0, "to": 1})
    e.finalize()
    assert seen == []  # bytes still buffered
    while (c := e.read()) not in (None, b""):
        pass
    assert seen == ["finish"]
    assert e.finished
    # late registration on a finished encoder fires immediately
    e.on_finish(lambda: seen.append("late"))
    assert seen == ["finish", "late"]


def test_encoder_destroy_fires_error_then_finish():
    """Teardown ordering parity: 'error' before 'close'
    (reference: encode.js:73-74)."""
    e = protocol.encode()
    order = []
    e.on_error(lambda err: order.append(("error", type(err).__name__)))
    e.on_finish(lambda: order.append(("finish", None)))
    e.change({"key": "k", "change": 1, "from": 0, "to": 1})
    e.destroy(RuntimeError("boom"))
    assert order == [("error", "RuntimeError"), ("finish", None)]
    # destroy after a clean finish must not re-fire
    e2 = protocol.encode()
    n = []
    e2.on_finish(lambda: n.append(1))
    e2.finalize()
    assert n == [1]
    e2.destroy()
    assert n == [1]


def test_encoder_immediate_finalize_fires_finish():
    e = protocol.encode()
    seen = []
    e.on_finish(lambda: seen.append(1))
    e.finalize()  # nothing queued: drained already
    assert seen == [1]


def test_encoder_double_pump_attach_fails_loudly():
    """Advisor: a second pump must not silently clobber the first's
    readable hook (which would park it forever)."""
    e = protocol.encode()
    e._attach_readable(lambda: None)
    with pytest.raises(RuntimeError, match="already attached"):
        e._attach_readable(lambda: None)
    e._detach_readable()
    e._attach_readable(lambda: None)  # re-attach after detach is fine


def test_tree_sync_truncated_reply_rejected():
    """Advisor: a truncated differ-bitmap must raise, not silently report
    the dropped tail as in-sync."""
    from dat_replication_protocol_tpu.ops import merkle
    from dat_replication_protocol_tpu.runtime.tree_sync import TreeSyncSession

    hh, hl = merkle.digests_to_device([bytes([i]) * 32 for i in range(16)])
    lvh, lvl = merkle.build_tree(hh, hl)
    s = TreeSyncSession(lvh, lvl)
    frontier = list(range(8))  # 16 kids -> 2 bitmap bytes
    with pytest.raises(ValueError, match="differ-bitmap"):
        s.next_frontier(frontier, b"\x00")  # one byte short


def test_pipe_releases_encoder_hook_after_eof():
    """A completed pipe must free the encoder's readable slot so a later
    transport pump can claim it (attach is exclusive)."""
    e = protocol.encode()
    d = protocol.decode()
    d.change(lambda c, done: done())
    e.change({"key": "k", "change": 1, "from": 0, "to": 1})
    e.finalize()
    p = protocol.pipe(e, d)
    assert p.done
    e._attach_readable(lambda: None)  # must not raise after EOF release


def test_pipe_releases_encoder_hook_on_decoder_destroy():
    """A decoder destroyed outside an active pump frees the encoder's
    readable slot at once — re-piping to a fresh decoder must work."""
    e = protocol.encode()
    d = protocol.decode()
    d.change(lambda c, done: done())
    e.change({"key": "k", "change": 1, "from": 0, "to": 1})
    protocol.pipe(e, d)
    d.destroy(RuntimeError("app error outside pump"))
    d2 = protocol.decode()
    got = []
    d2.change(lambda c, done: (got.append(c.key), done()))
    e.change({"key": "k2", "change": 2, "from": 1, "to": 2})
    e.finalize()
    protocol.pipe(e, d2)  # must not raise; pumps the remaining frames
    assert got == ["k2"]
