"""The literal sidecar endpoint (round-4 verdict missing #4 / next #6).

The client side of every test is a FOREIGN client: raw wire bytes on a
socket or pipe — no package Encoder — using the hand-derived reference
transcripts from test_wire_fixtures (their wire, reference:
test/basic.js), so these tests prove a non-Python process could pipe
into the TPU data plane exactly the way the reference pipes into a
socket (reference: example.js:53).
"""

import hashlib
import socket
import subprocess
import sys
import threading
import time

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu import sidecar

from test_wire_fixtures import CHANGE_PAYLOAD, SESSION_1, SESSION_4


def _decode_reply(raw: bytes) -> list:
    """Parse the sidecar's reply stream with an independent decoder."""
    out = []
    dec = protocol.decode()
    dec.change(lambda ch, done: (out.append(ch), done()))
    dec.write(raw)
    dec.end()
    assert dec.finished
    return out


def _recv_all(sock: socket.socket) -> bytes:
    parts = []
    while True:
        d = sock.recv(65536)
        if not d:
            return b"".join(parts)
        parts.append(d)


def test_tcp_sidecar_serves_reference_transcript_session_1():
    ready = threading.Event()
    port_box = {}

    def run():
        sidecar.serve_tcp(
            "127.0.0.1", 0, max_sessions=1,
            ready_cb=lambda p: (port_box.__setitem__("p", p), ready.set()),
        )

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert ready.wait(10)
    c = socket.create_connection(("127.0.0.1", port_box["p"]), timeout=10)
    c.sendall(SESSION_1)  # THEIR bytes: one change frame
    c.shutdown(socket.SHUT_WR)
    reply = _decode_reply(_recv_all(c))
    c.close()
    t.join(timeout=10)
    assert len(reply) == 1
    ch = reply[0]
    assert ch.key == "change-0" and ch.subset == "digest:change"
    assert ch.value == hashlib.blake2b(
        CHANGE_PAYLOAD, digest_size=32).digest()


def test_tcp_sidecar_blob_and_change_session_4():
    ready = threading.Event()
    port_box = {}
    t = threading.Thread(
        target=sidecar.serve_tcp,
        args=("127.0.0.1", 0),
        kwargs=dict(max_sessions=1,
                    ready_cb=lambda p: (port_box.__setitem__("p", p),
                                        ready.set())),
        daemon=True,
    )
    t.start()
    assert ready.wait(10)
    c = socket.create_connection(("127.0.0.1", port_box["p"]), timeout=10)
    c.sendall(SESSION_4)  # blob 'hello world' then the parked change
    c.shutdown(socket.SHUT_WR)
    reply = _decode_reply(_recv_all(c))
    c.close()
    by_key = {ch.key: ch for ch in reply}
    assert set(by_key) == {"blob-0", "change-0"}
    assert by_key["blob-0"].value == hashlib.blake2b(
        b"hello world", digest_size=32).digest()
    assert by_key["blob-0"].subset == "digest:blob"
    assert by_key["change-0"].value == hashlib.blake2b(
        CHANGE_PAYLOAD, digest_size=32).digest()


def test_tcp_sidecar_protocol_error_closes_connection():
    ready = threading.Event()
    port_box = {}
    t = threading.Thread(
        target=sidecar.serve_tcp,
        args=("127.0.0.1", 0),
        kwargs=dict(max_sessions=1,
                    ready_cb=lambda p: (port_box.__setitem__("p", p),
                                        ready.set())),
        daemon=True,
    )
    t.start()
    assert ready.wait(10)
    c = socket.create_connection(("127.0.0.1", port_box["p"]), timeout=10)
    c.settimeout(15)
    c.sendall(b"\xff" * 64)  # hostile length varint
    # the sidecar must answer with EOF (destroy cascade), never hang
    assert _recv_all(c) == b""
    c.close()
    t.join(timeout=10)


def test_stdio_sidecar_subprocess_roundtrip():
    """The deployment shape itself: a separate OS process, wire bytes on
    stdin, digest session on stdout."""
    import os

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    # the dev image's sitecustomize re-forces the tunneled platform in
    # fresh interpreters; a wedged tunnel would hang the digest engine's
    # first dispatch.  The routing layer's own override pins the child
    # to the host engine — the test exercises the process boundary and
    # wire contract, not the device.
    env["DAT_DEVICE_HASH"] = "0"
    proc = subprocess.Popen(
        [sys.executable, "-m", "dat_replication_protocol_tpu.sidecar",
         "--stdio", "--backend", "tpu"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        cwd=repo_root, env=env,
    )
    out, err = proc.communicate(SESSION_4, timeout=120)
    assert proc.returncode == 0, err.decode()
    reply = _decode_reply(out)
    assert {ch.key for ch in reply} == {"blob-0", "change-0"}
    assert all(len(ch.value) == 32 for ch in reply)


def test_tcp_sidecar_survives_client_vanishing_mid_reply():
    """A client that closes its whole socket before reading the reply
    must not hang the session thread or crash the daemon (the sender's
    EPIPE tears down both directions)."""
    ready = threading.Event()
    port_box = {}
    t = threading.Thread(
        target=sidecar.serve_tcp,
        args=("127.0.0.1", 0),
        kwargs=dict(max_sessions=1,
                    ready_cb=lambda p: (port_box.__setitem__("p", p),
                                        ready.set())),
        daemon=True,
    )
    t.start()
    assert ready.wait(10)
    c = socket.create_connection(("127.0.0.1", port_box["p"]), timeout=10)
    c.sendall(SESSION_1)
    # vanish entirely: RST-ish close with the reply unread
    c.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                 b"\x01\x00\x00\x00\x00\x00\x00\x00")
    c.close()
    t.join(timeout=30)
    assert not t.is_alive(), "serve loop hung on a vanished client"


def test_run_session_tears_down_stalled_reply_drain():
    """A client that finishes sending but never reads its reply must not
    park the session thread forever (ADVICE.md round 5: the healthy path
    ended in a bare sender.join()).  After drain_timeout with no reply
    progress, run_session destroys the encoder, fires close_write to
    unblock the parked sender (the socket-shutdown EPIPE analogue), and
    returns ok=False — bounded, observable teardown instead of a
    per-connection thread leak."""
    import time

    fed = {"done": False}

    def read_bytes(n):
        if fed["done"]:
            return b""  # EOF: the client finished sending
        fed["done"] = True
        return SESSION_1

    released = threading.Event()
    closed = threading.Event()

    def write_bytes(data):
        if closed.is_set():
            raise OSError("EPIPE")
        # a peer with a full receive window that never reads: the write
        # parks until close_write "shuts the socket down" under it
        released.wait(30)
        raise OSError("EPIPE")

    def close_write():
        closed.set()
        released.set()

    t0 = time.monotonic()
    stats = sidecar.run_session(read_bytes, write_bytes,
                                close_write=close_write,
                                drain_timeout=1.0)
    elapsed = time.monotonic() - t0
    assert closed.is_set(), "stall teardown never fired close_write"
    assert elapsed < 15, f"drain teardown took {elapsed:.1f}s"
    assert stats["ok"] is False  # a stalled session must not report ok
    assert stats["changes"] == 1 and stats["digests"] == 1


def test_slow_upload_then_burst_is_not_torn_down_as_stalled():
    """The mid-session digest-flush stall check must measure the stall
    from when the backpressure wait STARTS, not from the last reply
    byte: a client that uploads quietly for longer than drain_timeout
    (one huge blob, no digest traffic) and then triggers a reply burst
    that crosses the encoder high-water mark is healthy — pre-fix the
    first 0.1s poll compared against the stale progress clock and tore
    the session down with TimeoutError while the client was reading
    promptly (drain-loop parity: serve-side line resets the clock at
    drain entry; this wait did not)."""
    import time

    enc = protocol.encode()
    n = 1400  # digest replies ~60B framed each: crosses the 64 KiB HW
    for i in range(n):
        enc.change({"key": f"k{i}", "change": i, "from": 0, "to": 1,
                    "value": b"x" * 8})
    enc.finalize()
    wire = enc.read()

    state = {"fed": False}

    def read_bytes(_n):
        if state["fed"]:
            return b""
        state["fed"] = True
        # quiet upload stretch longer than drain_timeout, THEN the burst
        time.sleep(2.0)
        return wire

    release = threading.Event()
    writes = []

    def write_bytes(data):
        # healthy-but-momentarily-busy peer: the first write is in
        # flight for ~0.5s (well under drain_timeout) while the digest
        # burst crosses the high-water mark behind it
        if not writes:
            writes.append(len(data))
            release.wait(10)
        else:
            writes.append(len(data))

    threading.Timer(2.5, release.set).start()
    stats = sidecar.run_session(read_bytes, write_bytes,
                                close_write=lambda: None,
                                drain_timeout=1.5)
    assert stats["ok"] is True, f"healthy session torn down: {stats}"
    assert stats["digests"] == n


def test_stall_teardown_with_inflight_digest_batches(monkeypatch):
    """Reply stall while the PIPELINED digest engine (ISSUE 7: jitted
    batch dispatches, prefetched readback) still holds in-flight work:
    the drain teardown must stay bounded — the flush-before-finalize
    barrier parked behind a stalled reply cannot deadlock the session
    thread against its own outstanding batches."""
    import time

    monkeypatch.setenv("DAT_DEVICE_HASH", "1")  # the jitted batch engine

    enc = protocol.encode()
    n = 1200  # enough digest replies to cross the encoder high-water
    for i in range(n):
        enc.change({"key": f"k{i}", "change": i, "from": 0, "to": 1,
                    "value": b"x" * 16})
    enc.finalize()
    wire = enc.read()

    state = {"fed": False}

    def read_bytes(_n):
        if state["fed"]:
            return b""
        state["fed"] = True
        return wire

    released = threading.Event()
    closed = threading.Event()

    def write_bytes(data):
        if closed.is_set():
            raise OSError("EPIPE")
        released.wait(30)  # the client never reads its reply
        raise OSError("EPIPE")

    def close_write():
        closed.set()
        released.set()

    t0 = time.monotonic()
    stats = sidecar.run_session(read_bytes, write_bytes,
                                close_write=close_write,
                                drain_timeout=1.0)
    elapsed = time.monotonic() - t0
    assert closed.is_set(), "stall teardown never fired close_write"
    assert elapsed < 20, f"teardown took {elapsed:.1f}s with batches in flight"
    assert stats["ok"] is False


# -- telemetry (ISSUE 3): stall events + --stats-fd machinery ----------------


def test_stall_teardown_emits_structured_stall_event(obs_enabled):
    """Satellite of ISSUE 3: the reply-drain deadline firing must be
    VISIBLE — a sidecar.stall event with the deadline and reply
    progress, plus the stalls counter — not just a silent teardown."""
    from dat_replication_protocol_tpu.obs.events import EVENTS

    fed = {"done": False}

    def read_bytes(n):
        if fed["done"]:
            return b""
        fed["done"] = True
        return SESSION_1

    released = threading.Event()
    closed = threading.Event()

    def write_bytes(data):
        if closed.is_set():
            raise OSError("EPIPE")
        released.wait(30)
        raise OSError("EPIPE")

    def close_write():
        closed.set()
        released.set()

    stats = sidecar.run_session(read_bytes, write_bytes,
                                close_write=close_write,
                                drain_timeout=0.5)
    assert stats["ok"] is False
    stalls = EVENTS.events("sidecar.stall")
    assert len(stalls) == 1
    assert stalls[0]["fields"]["kind"] == "reply-drain"
    assert stalls[0]["fields"]["seconds"] == 0.5
    assert obs_enabled.REGISTRY.counter("sidecar.stalls").value == 1
    # the session record rides the same event stream
    sessions = EVENTS.events("sidecar.session")
    assert len(sessions) == 1 and sessions[0]["fields"]["ok"] is False


def test_stats_emitter_kick_forces_immediate_parseable_dump(obs_enabled):
    import json
    import os

    obs_enabled.REGISTRY.counter("sidecar.test.marker").inc(7)
    r, w = os.pipe()
    emitter = sidecar.StatsEmitter(w, interval=60.0).start()
    try:
        emitter.kick()
        line = b""
        while not line.endswith(b"\n"):
            line += os.read(r, 65536)
        rec = json.loads(line.decode())
        assert rec["metrics"]["counters"]["sidecar.test.marker"] == 7
        assert "ts" in rec and "monotonic" in rec
        assert "events_dropped" in rec
    finally:
        emitter.stop()
        os.close(r)
        os.close(w)


def test_sigusr1_one_shot_dump(obs_enabled):
    import json
    import os
    import signal

    r, w = os.pipe()
    emitter = sidecar.StatsEmitter(w, interval=60.0).start()
    old = signal.getsignal(signal.SIGUSR1)
    try:
        assert sidecar._install_sigusr1(emitter)
        os.kill(os.getpid(), signal.SIGUSR1)
        line = b""
        while not line.endswith(b"\n"):
            line += os.read(r, 65536)
        rec = json.loads(line.decode())
        assert "metrics" in rec and "counters" in rec["metrics"]
    finally:
        signal.signal(signal.SIGUSR1, old)
        emitter.stop()
        os.close(r)
        os.close(w)


def test_stdio_sidecar_stats_fd_emits_parseable_snapshots():
    """ISSUE 3 acceptance: `sidecar --stats-fd` emits parseable JSON
    snapshots — end-to-end through main(), over a real inherited fd."""
    import json
    import os

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["DAT_DEVICE_HASH"] = "0"
    r, w = os.pipe()
    os.set_inheritable(w, True)
    proc = subprocess.Popen(
        [sys.executable, "-m", "dat_replication_protocol_tpu.sidecar",
         "--stdio", "--stats-fd", str(w), "--stats-interval", "0.2"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        cwd=repo_root, env=env, pass_fds=(w,), close_fds=True,
    )
    out, err = proc.communicate(SESSION_4, timeout=120)
    os.close(w)
    assert proc.returncode == 0, err.decode()
    raw = b""
    while True:
        chunk = os.read(r, 65536)
        if not chunk:
            break
        raw += chunk
    os.close(r)
    lines = [ln for ln in raw.decode().splitlines() if ln.strip()]
    assert lines, "no stats snapshots emitted"
    for ln in lines:
        rec = json.loads(ln)  # every line parses independently
        assert "metrics" in rec
    # the final pre-exit snapshot carries the session's whole story
    final = json.loads(lines[-1])["metrics"]["counters"]
    assert final["sidecar.sessions"] == 1
    assert final["decoder.digests"] == 2  # blob-0 + change-0
    # the reply stream's own encode traffic is attributed too
    assert final["encoder.changes"] == 2


def test_stats_emitter_prom_format_exposition(obs_enabled):
    """ISSUE 4 satellite: --stats-format prom renders Prometheus text
    exposition blocks (cumulative buckets, dat_ namespace)."""
    import os

    obs_enabled.REGISTRY.counter("sidecar.test.prom").inc(3)
    obs_enabled.REGISTRY.histogram("sidecar.test.lat").observe(0.5)
    r, w = os.pipe()
    emitter = sidecar.StatsEmitter(w, interval=60.0, fmt="prom").start()
    try:
        emitter.kick()
        raw = b""
        while b"dat_obs_scrape_ts" not in raw:
            raw += os.read(r, 65536)
        text = raw.decode()
        assert "# TYPE dat_sidecar_test_prom counter\n" \
               "dat_sidecar_test_prom 3" in text
        assert "# TYPE dat_sidecar_test_lat histogram" in text
        assert 'dat_sidecar_test_lat_bucket{le="+Inf"} 1' in text
        assert "dat_obs_events_dropped 0" in text
    finally:
        emitter.stop()
        os.close(r)
        os.close(w)


def test_stats_emitter_rejects_unknown_format():
    import pytest

    with pytest.raises(ValueError):
        sidecar.StatsEmitter(1, fmt="xml")


def test_stdio_sidecar_flight_dir_and_trace_jsonl(tmp_path):
    """ISSUE 4 tentpole wiring: a malformed foreign session through
    `--stdio --flight-dir --trace-jsonl` leaves (a) an atomic
    post-mortem bundle whose manifest carries the error coordinates
    and (b) a JSONL trace log the timeline CLI can consume."""
    import json
    import os

    from dat_replication_protocol_tpu.obs import flight

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["DAT_DEVICE_HASH"] = "0"
    flight_dir = str(tmp_path / "flight")
    trace_log = str(tmp_path / "sidecar.jsonl")
    proc = subprocess.Popen(
        [sys.executable, "-m", "dat_replication_protocol_tpu.sidecar",
         "--stdio", "--flight-dir", flight_dir,
         "--trace-jsonl", trace_log],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, cwd=repo_root, env=env,
    )
    # valid change frame first, then garbage: type id 9 is a wire error
    out, err = proc.communicate(SESSION_1 + b"\x05\x09zzzz", timeout=120)
    assert proc.returncode == 1, err.decode()  # ok: False
    bundles = [n for n in os.listdir(flight_dir)
               if not n.startswith(".")]
    assert len(bundles) == 1 and "protocol-error" in bundles[0], bundles
    b = flight.read_bundle(os.path.join(flight_dir, bundles[0]))
    assert b["manifest"]["error"]["type"] == "ProtocolError"
    assert b["manifest"]["error"]["offset"] is not None
    assert any(e.get("event") == "protocol.error" for e in b["events"])
    # the trace log holds the decoder's wire-offset frame spans
    records = [json.loads(ln)
               for ln in open(trace_log).read().splitlines() if ln]
    frames = [r for r in records if r.get("span") == "decoder.frame"]
    assert frames and frames[0]["fields"]["offset"] == 0


# -- hub mode (ISSUE 8): shared engine, per-session drain + stats ------------


def test_hub_mode_drain_timeout_is_per_session():
    """Satellite of ISSUE 8: in hub mode --drain-timeout applies PER
    SESSION.  Session A stalls its reply and must be torn down at ~its
    own deadline (not extended by B's liveness); session B uploads
    slowly past A's teardown and must complete ok (not cut short by A's
    deadline firing)."""
    import time

    from dat_replication_protocol_tpu.hub import ReplicationHub

    hub = ReplicationHub(linger_s=0.002)
    results = {}

    def session_a():
        fed = {"done": False}

        def read_bytes(n):
            if fed["done"]:
                return b""
            fed["done"] = True
            return SESSION_1

        released = threading.Event()
        closed = threading.Event()

        def write_bytes(data):
            if closed.is_set():
                raise OSError("EPIPE")
            released.wait(30)  # never reads its reply
            raise OSError("EPIPE")

        def close_write():
            closed.set()
            released.set()

        t0 = time.monotonic()
        stats = sidecar.run_session(read_bytes, write_bytes,
                                    close_write=close_write,
                                    drain_timeout=1.0,
                                    hub=hub, session_key="staller")
        results["a"] = (stats, time.monotonic() - t0)

    def session_b():
        state = {"i": 0}
        chunks = [SESSION_4[i:i + 8] for i in range(0, len(SESSION_4), 8)]

        def read_bytes(n):
            # a healthy-but-slow upload: ~2.5s total, well past A's
            # 1s deadline — B's own clock must not be contaminated
            if state["i"] >= len(chunks):
                return b""
            time.sleep(2.5 / len(chunks))
            chunk = chunks[state["i"]]
            state["i"] += 1
            return chunk

        reply = []
        t0 = time.monotonic()
        stats = sidecar.run_session(read_bytes, reply.append,
                                    close_write=lambda: None,
                                    drain_timeout=1.0,
                                    hub=hub, session_key="slowpoke")
        results["b"] = (stats, time.monotonic() - t0)

    ta = threading.Thread(target=session_a, daemon=True)
    tb = threading.Thread(target=session_b, daemon=True)
    ta.start()
    tb.start()
    ta.join(20)
    tb.join(20)
    assert not ta.is_alive() and not tb.is_alive(), "HANG"
    hub.close()
    stats_a, elapsed_a = results["a"]
    stats_b, elapsed_b = results["b"]
    # A: torn down on ITS deadline — not extended while B kept running
    assert stats_a["ok"] is False and stats_a["session"] == "staller"
    assert elapsed_a < 2.4, f"A's teardown waited on B: {elapsed_a:.1f}s"
    # B: completed past A's teardown — not cut short by A's deadline
    assert stats_b["ok"] is True, f"B torn down by A's deadline: {stats_b}"
    assert stats_b["session"] == "slowpoke"
    assert stats_b["digests"] == 2
    assert elapsed_b > 2.0


def test_hub_mode_stats_fd_lines_carry_sessions_breakdown(obs_enabled):
    """Satellite of ISSUE 8: --stats-fd snapshots in hub mode carry a
    per-session `sessions` breakdown that cross-checks against the
    hub's own per-session stats (the oracle contract)."""
    import json
    import os

    from dat_replication_protocol_tpu.hub import ReplicationHub

    hub = ReplicationHub(hash_batch=lambda ps: [
        hashlib.blake2b(p, digest_size=32).digest() for p in ps])
    sidecar.set_active_hub(hub)
    try:
        a = hub.register("peer-a")
        b = hub.register("peer-b")
        got = []
        for i in range(9):
            a.submit(b"payload-%d" % i, lambda d: got.append(d))
        a.flush()
        r, w = os.pipe()
        emitter = sidecar.StatsEmitter(w, interval=60.0).start()
        try:
            emitter.kick()
            line = b""
            while not line.endswith(b"\n"):
                line += os.read(r, 65536)
            rec = json.loads(line.decode())
        finally:
            emitter.stop()
            os.close(r)
            os.close(w)
        # the line's breakdown == the hub's live per-session stats
        assert rec["hub"]["sessions"] == 2
        per = rec["sessions"]
        assert set(per) == {"peer-a", "peer-b"}
        assert per["peer-a"]["submitted"] == 9
        assert per["peer-a"]["delivered"] == 9
        assert per["peer-b"]["submitted"] == 0
        assert per["peer-a"] == hub.sessions_snapshot()["peer-a"]
        # the registry snapshot in the SAME line carries the labeled
        # per-session collector entries (hub.session.* family)
        counters = rec["metrics"]["counters"]
        assert counters["hub.session.submitted{session=peer-a}"] == 9
        assert rec["metrics"]["gauges"]["hub.sessions"] == 2.0
        a.close()
        b.close()
    finally:
        sidecar.set_active_hub(None)
        hub.close()


def test_hub_mode_session_record_cross_checks_driver_stats(obs_enabled):
    """The conformance-oracle arm: run_session's returned driver stats,
    the sidecar.session event, and the hub's dispatch counters must all
    tell the same story for a keyed hub session."""
    from dat_replication_protocol_tpu.hub import ReplicationHub
    from dat_replication_protocol_tpu.obs.events import EVENTS

    hub = ReplicationHub(linger_s=0.002)
    try:
        fed = {"done": False}

        def read_bytes(n):
            if fed["done"]:
                return b""
            fed["done"] = True
            return SESSION_4

        reply = []
        stats = sidecar.run_session(read_bytes, reply.append,
                                    close_write=lambda: None,
                                    hub=hub, session_key="oracle-k")
        assert stats["ok"] is True
        assert stats["session"] == "oracle-k" and stats["shed"] is None
        assert stats["digests"] == 2  # blob-0 + change-0
        ev = EVENTS.events("sidecar.session")[-1]["fields"]
        assert ev["session"] == "oracle-k"
        assert ev["digests"] == stats["digests"]
        reg = obs_enabled.REGISTRY
        assert reg.counter("hub.dispatch.items").value == stats["digests"]
        assert reg.counter("hub.admitted").value == 1
        # the slot was released at session end (bounded cardinality)
        assert hub.sessions_snapshot() == {}
    finally:
        hub.close()


def test_hub_mode_admission_rejection_is_structured(obs_enabled):
    """A connection past the admission bound gets a structured
    rejection record and EOF — no decoder, no queue growth."""
    from dat_replication_protocol_tpu.hub import ReplicationHub
    from dat_replication_protocol_tpu.obs.events import EVENTS

    hub = ReplicationHub(max_sessions=1)
    try:
        held = hub.register("occupant")
        closed = []
        stats = sidecar.run_session(
            lambda n: SESSION_1, lambda d: None,
            close_write=lambda: closed.append(True),
            hub=hub, session_key="refused")
        assert stats == {"changes": 0, "blobs": 0, "bytes": 0,
                         "digests": 0, "ok": False, "rejected": True,
                         "sessions": 1, "parked_bytes": 0}
        assert closed, "rejected connection was not closed"
        rejects = EVENTS.events("hub.reject")
        assert rejects and rejects[-1]["fields"]["key"] == "refused"
        held.close()
    finally:
        hub.close()


# -- fan-out mode (ISSUE 9) ---------------------------------------------------


def test_tcp_sidecar_fanout_broadcasts_source_wire_to_subscribers():
    """--fanout shape: the FIRST connection is the source session
    (decoded + digested once, reply streamed back); later connections
    are subscribers that receive the source's wire bytes byte-exactly
    via the zero-copy writev fan-out — including a late joiner that
    attaches mid-stream."""
    from dat_replication_protocol_tpu.fanout import FanoutServer

    fanout = FanoutServer(stall_timeout=10.0)
    ready = threading.Event()
    port_box = {}
    t = threading.Thread(
        target=sidecar.serve_tcp,
        args=("127.0.0.1", 0),
        kwargs=dict(max_sessions=3, fanout=fanout,
                    ready_cb=lambda p: (port_box.__setitem__("p", p),
                                        ready.set())),
        daemon=True,
    )
    t.start()
    assert ready.wait(10)
    addr = ("127.0.0.1", port_box["p"])

    src = socket.create_connection(addr, timeout=10)
    half = len(SESSION_4) // 2
    src.sendall(SESSION_4[:half])

    # subscriber 1 joins mid-stream (offset 0 is still retained)
    sub1 = socket.create_connection(addr, timeout=10)

    src.sendall(SESSION_4[half:])
    src.shutdown(socket.SHUT_WR)
    reply = _decode_reply(_recv_all(src))
    src.close()
    by_key = {ch.key: ch for ch in reply}
    assert set(by_key) == {"blob-0", "change-0"}  # digested ONCE, at source

    # late joiner: the source may already be sealed — retention serves it
    sub2 = socket.create_connection(addr, timeout=10)

    got1 = _recv_all(sub1)
    got2 = _recv_all(sub2)
    sub1.close()
    sub2.close()
    t.join(timeout=10)
    fanout.close()
    assert got1 == SESSION_4  # byte-exact broadcast
    assert got2 == SESSION_4


def test_fanout_subscriber_past_retention_gets_snapshot_needed():
    """A joiner below the retained window gets the structured
    snapshot-needed record and EOF — never silently wrong bytes."""
    import json as _json

    from dat_replication_protocol_tpu.fanout import FanoutServer

    fanout = FanoutServer(retention_budget=64, stall_timeout=5.0)
    try:
        fanout.publish(b"x" * 400)  # budget-trims the head immediately
        fanout.log.enforce_retention()
        a, b = socket.socketpair()
        out = sidecar.run_subscriber(a, fanout, key="late")
        assert out["ok"] is False and out["snapshot_needed"] is True
        assert out["retained"] == [400 - 64, 400]
        line = _recv_all(b)
        rec = _json.loads(line.decode())
        assert rec["snapshot_needed"] is True
        assert rec["retained"] == [336, 400]
        a.close()
        b.close()
    finally:
        fanout.close()


def test_fanout_stats_snapshot_carries_peer_breakdown(obs_enabled):
    """--stats-fd lines in fan-out mode answer "which peer is lagging":
    the snapshot carries the fan-out aggregate and per-peer stats, and
    the registry collector exposes labeled per-peer series."""
    from dat_replication_protocol_tpu.fanout import FanoutServer
    from dat_replication_protocol_tpu.obs import metrics as obs_metrics

    fanout = FanoutServer(stall_timeout=5.0)
    sidecar.set_active_fanout(fanout)
    try:
        got = bytearray()

        def sink(views):
            n = 0
            for v in views:
                got.extend(bytes(v))
                n += len(v)
            return n

        peer = fanout.attach_peer("k1", sink=sink)
        fanout.publish(b"z" * 5000)
        fanout.seal()
        assert fanout.drain(10)
        snap = sidecar.snapshot_stats()
        assert snap["fanout"]["peers"] == 1
        assert snap["fanout"]["sealed"] is True
        assert snap["peers"]["k1"]["sent_bytes"] == 5000
        assert snap["peers"]["k1"]["shed"] is None
        reg_snap = obs_metrics.snapshot()
        assert reg_snap["counters"]["fanout.peer.sent_bytes{peer=k1}"] == 5000
        assert reg_snap["gauges"]["fanout.peers"] == 1.0
        peer.close()
        assert bytes(got) == b"z" * 5000
    finally:
        sidecar.set_active_fanout(None)
        fanout.close()


def test_fanout_probe_connection_does_not_brick_the_broadcast():
    """Review regression: a stray first connection that closes without
    publishing a byte (healthcheck, port scan) must RELEASE the source
    claim — the real source connecting afterwards still broadcasts."""
    from dat_replication_protocol_tpu.fanout import FanoutServer

    fanout = FanoutServer(stall_timeout=10.0)
    ready = threading.Event()
    port_box = {}
    t = threading.Thread(
        target=sidecar.serve_tcp,
        args=("127.0.0.1", 0),
        kwargs=dict(max_sessions=3, fanout=fanout,
                    ready_cb=lambda p: (port_box.__setitem__("p", p),
                                        ready.set())),
        daemon=True,
    )
    t.start()
    assert ready.wait(10)
    addr = ("127.0.0.1", port_box["p"])

    probe = socket.create_connection(addr, timeout=10)
    probe.close()  # the healthcheck: no bytes, instant close
    time.sleep(0.3)  # let its session thread release the claim
    assert not fanout.log.sealed

    src = socket.create_connection(addr, timeout=10)
    src.sendall(SESSION_1)
    src.shutdown(socket.SHUT_WR)
    reply = _decode_reply(_recv_all(src))
    src.close()
    assert len(reply) == 1  # the REAL source was decoded + digested

    sub = socket.create_connection(addr, timeout=10)
    got = _recv_all(sub)
    sub.close()
    t.join(timeout=10)
    fanout.close()
    assert got == SESSION_1


def test_fanout_idle_subscriber_disconnect_releases_slot():
    """Review regression: a caught-up subscriber that disconnects while
    the broadcast is idle (no bytes in flight to surface an EPIPE) must
    release its peer slot instead of leaking it until new traffic."""
    from dat_replication_protocol_tpu.fanout import FanoutServer

    fanout = FanoutServer(stall_timeout=30.0)
    fanout.publish(b"x" * 1000)  # subscribers catch up, log stays open
    try:
        a, b = socket.socketpair()
        out = {}

        def run():
            out["stats"] = sidecar.run_subscriber(a, fanout, key="ghost")

        t = threading.Thread(target=run, daemon=True)
        t.start()
        # wait until the broadcast reached the subscriber
        deadline = time.monotonic() + 5
        got = bytearray()
        b.settimeout(5)
        while len(got) < 1000 and time.monotonic() < deadline:
            got.extend(b.recv(4096))
        assert bytes(got) == b"x" * 1000
        b.close()  # client goes away; the log is idle and unsealed
        t.join(10)
        assert not t.is_alive(), "subscriber thread leaked"
        assert fanout.peers_snapshot() == {}  # the slot was released
        a.close()
    finally:
        fanout.close()


def test_fanout_rejected_subscriber_gets_structured_record():
    """Review regression: a FanoutBusy rejection must SEND its
    structured record — a bare EOF is indistinguishable from an empty
    sealed broadcast."""
    import json as _json

    from dat_replication_protocol_tpu.fanout import FanoutServer

    fanout = FanoutServer(max_peers=1, stall_timeout=5.0)
    try:
        held = fanout.attach_peer("occupant", sink=lambda vs: 0)
        a, b = socket.socketpair()
        out = sidecar.run_subscriber(a, fanout, key="refused")
        assert out["ok"] is False and out["rejected"] is True
        assert out["peers"] == 1 and out["max_peers"] == 1
        rec = _json.loads(_recv_all(b).decode())
        assert rec["rejected"] is True and rec["max_peers"] == 1
        a.close()
        b.close()
        held.close()
    finally:
        fanout.close()


def test_fanout_misrouted_source_fails_loudly_not_silently():
    """Review regression: a subscriber connection that SENDS data is a
    source that lost the claim race — it must get a structured
    not_source record and EOF, never have its session silently
    discarded."""
    import json as _json

    from dat_replication_protocol_tpu.fanout import FanoutServer

    fanout = FanoutServer(stall_timeout=10.0)
    try:
        a, b = socket.socketpair()
        out_box = {}

        def run():
            out_box["out"] = sidecar.run_subscriber(a, fanout, key="mis")

        t = threading.Thread(target=run, daemon=True)
        t.start()
        time.sleep(0.2)
        b.sendall(SESSION_1)  # "I am a source" — wrong slot
        t.join(10)
        assert not t.is_alive()
        out = out_box["out"]
        assert out["ok"] is False and out["not_source"] is True
        raw = _recv_all(b)
        rec = _json.loads(raw.splitlines()[-1].decode())
        assert rec["not_source"] is True
        assert fanout.peers_snapshot() == {}  # slot released
        a.close()
        b.close()
    finally:
        fanout.close()


# -- anti-entropy mode (ISSUE 10) ---------------------------------------------


def test_tcp_sidecar_reconcile_exchanges_exact_diff(tmp_path):
    """--reconcile shape: the daemon answers a reconcile initiator from
    a change-log wire file — the two sides exchange exactly their
    differing records over O(diff) wire, and every extra connection is
    its own independent session against the shared (read-only)
    replica."""
    from dat_replication_protocol_tpu.runtime import replay
    from dat_replication_protocol_tpu.runtime.reconcile_driver import (
        RatelessReplica,
        run_initiator,
    )

    def log_bytes(keys):
        return replay.encode_change_log(
            [{"key": k, "change": i, "from": i, "to": i + 1,
              "value": b"v:" + k.encode()} for i, k in enumerate(keys)])

    keys = [f"key-{i:05d}" for i in range(400)]
    logfile = tmp_path / "srv_log.bin"
    logfile.write_bytes(log_bytes(keys + ["srv-only-1", "srv-only-2"]))
    client = RatelessReplica(log_bytes(keys + ["cli-only"]))

    replica = sidecar.load_reconcile_replica(str(logfile))
    ready = threading.Event()
    port_box = {}
    t = threading.Thread(
        target=sidecar.serve_tcp,
        args=("127.0.0.1", 0),
        kwargs=dict(max_sessions=2, reconcile_replica=replica,
                    ready_cb=lambda p: (port_box.__setitem__("p", p),
                                        ready.set())),
        daemon=True,
    )
    t.start()
    assert ready.wait(10)
    addr = ("127.0.0.1", port_box["p"])

    for _ in range(2):  # a second session against the same replica
        c = socket.create_connection(addr, timeout=10)
        out = run_initiator(
            client, c.recv, c.sendall,
            close_write=lambda c=c: c.shutdown(socket.SHUT_WR))
        c.close()
        assert out["ok"]
        assert out["records_sent"] == 1  # cli-only, requested by the daemon
        assert {ch.key for ch in out["received"]} == {"srv-only-1",
                                                      "srv-only-2"}
    t.join(timeout=10)


def test_sidecar_reconcile_corrupt_stream_fails_structured():
    """A garbage initiator against --reconcile observes the FAIL frame
    + EOF; the session record carries the structured error — never a
    hang (the reconcile failure contract at the daemon edge)."""
    from dat_replication_protocol_tpu.runtime.reconcile_driver import (
        RatelessReplica,
    )
    from dat_replication_protocol_tpu.session.transport import once
    from dat_replication_protocol_tpu.wire import reconcile_codec as rcc
    from dat_replication_protocol_tpu.wire.framing import (
        TYPE_RECONCILE,
        frame,
    )

    replica = RatelessReplica([
        {"key": "a", "change": 1, "from": 0, "to": 1, "value": b"x"}])
    a, b = socket.socketpair()
    a.settimeout(10)
    b.settimeout(10)
    box = {}

    def serve():
        box["out"] = sidecar.run_reconcile_session(
            b.recv, b.sendall,
            once(lambda: b.shutdown(socket.SHUT_WR)), replica)

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    # symbols with a bad subtype byte: structural corruption
    a.sendall(frame(TYPE_RECONCILE, rcc.encode_begin(1)))
    a.sendall(frame(TYPE_RECONCILE, bytes([99, 1, 2, 3])))
    a.shutdown(socket.SHUT_WR)
    _recv_all(a)  # daemon closes its side: EOF, not a hang
    t.join(10)
    assert not t.is_alive()
    out = box["out"]
    assert out["reconcile"] is True and out["ok"] is False
    assert "error" in out
    a.close()
    b.close()


# -- snapshot bootstrap mode (ISSUE 12) --------------------------------------


def _snapshot_dataset(n=1 << 18, seed=0):
    import numpy as np

    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8)


def test_tcp_sidecar_snapshot_serves_cold_and_stale_joiners(tmp_path):
    """--snapshot shape: the daemon materializes DATAFILE once and
    every connection is an independent joiner session — a cold joiner
    streams the shared full-manifest log, a stale one reconciles and
    moves O(diff) bytes."""
    import numpy as np

    from dat_replication_protocol_tpu.runtime.snapshot_driver import (
        run_snapshot_joiner,
    )

    data = _snapshot_dataset()
    datafile = tmp_path / "dataset.bin"
    datafile.write_bytes(data.tobytes())
    source = sidecar.load_snapshot_source(str(datafile), wire_offset=99)
    stale = data.copy()
    stale[:: len(data) // 8] ^= 0x5A  # a few divergent chunks

    ready = threading.Event()
    port_box = {}
    t = threading.Thread(
        target=sidecar.serve_tcp,
        args=("127.0.0.1", 0),
        kwargs=dict(max_sessions=2, snapshot_source=source,
                    ready_cb=lambda p: (port_box.__setitem__("p", p),
                                        ready.set())),
        daemon=True,
    )
    t.start()
    assert ready.wait(10)
    addr = ("127.0.0.1", port_box["p"])

    c = socket.create_connection(addr, timeout=10)
    cold = run_snapshot_joiner(
        c.recv, c.sendall, lambda: c.shutdown(socket.SHUT_WR))
    c.close()
    assert cold["data"] == data.tobytes()
    assert cold["wire_offset"] == 99  # where the live session attaches

    c = socket.create_connection(addr, timeout=10)
    out = run_snapshot_joiner(
        c.recv, c.sendall, lambda: c.shutdown(socket.SHUT_WR),
        have=stale.tobytes())
    c.close()
    assert out["data"] == data.tobytes()
    assert out["chunks_reused"] > 0
    assert out["bytes_received"] < len(data) // 2  # O(diff), not O(n)
    t.join(timeout=10)
    assert np.array_equal(source._buf, data)  # source untouched


def test_fanout_snapshot_needed_record_carries_hint_and_redirect_works(
        tmp_path):
    """The composition aha (ISSUE 12): a subscriber trimmed past the
    broadcast window gets the structured snapshot-needed record WITH
    the bootstrap hint, dials the hinted port, and assembles the
    dataset — no out-of-band config anywhere."""
    import json as _json

    from dat_replication_protocol_tpu.fanout import FanoutServer
    from dat_replication_protocol_tpu.runtime.snapshot_driver import (
        run_snapshot_joiner,
    )
    from dat_replication_protocol_tpu.wire.framing import CAP_SNAPSHOT

    data = _snapshot_dataset(1 << 16, seed=3)
    datafile = tmp_path / "dataset.bin"
    datafile.write_bytes(data.tobytes())
    source = sidecar.load_snapshot_source(str(datafile))

    listener = sidecar.SnapshotListener(source, "127.0.0.1", 0)
    fanout = FanoutServer(retention_budget=64, stall_timeout=5.0,
                          snapshot_hint={"port": listener.port,
                                         "cap": CAP_SNAPSHOT})
    try:
        fanout.publish(b"x" * 400)  # budget-trims the head immediately
        fanout.log.enforce_retention()
        a, b = socket.socketpair()
        out = sidecar.run_subscriber(a, fanout, key="late")
        assert out["ok"] is False and out["snapshot_needed"] is True
        assert out["hint"] == {"port": listener.port, "cap": CAP_SNAPSHOT}
        rec = _json.loads(_recv_all(b).decode())
        a.close()
        b.close()
        assert rec["snapshot_needed"] is True
        assert rec["hint"]["cap"] == CAP_SNAPSHOT

        # ... and the hint WORKS: dial it, bootstrap, done
        c = socket.create_connection(("127.0.0.1", rec["hint"]["port"]),
                                     timeout=10)
        got = run_snapshot_joiner(
            c.recv, c.sendall, lambda: c.shutdown(socket.SHUT_WR))
        c.close()
        assert got["data"] == data.tobytes()
    finally:
        listener.close()
        fanout.close()


def test_sidecar_snapshot_cli_flags(capsys):
    """--snapshot refuses the modes it cannot compose with, keeping the
    CLI contract explicit."""
    import pytest

    with pytest.raises(SystemExit):
        sidecar.main(["--stdio", "--snapshot", "x.bin", "--hub"])
    err = capsys.readouterr().err
    assert "--snapshot cannot combine" in err


# -- blocking-reachability regression tests (ISSUE 16) ------------------------
# The readiness certifier (artifacts/event_loop_surface.json) found two
# true positives: StatsEmitter's EAGAIN/deadline machinery only engages
# on a NONBLOCKING fd, and the subscriber refusal path sendall()'d on a
# default-blocking socket.  These tests prove the bounds are real — on
# the pre-fix code both hang forever, so each runs the suspect call on
# a daemon thread and asserts it RETURNS instead of letting a
# regression wedge the whole suite.


def test_stats_emitter_full_pipe_skips_within_grace_bound():
    """A stats pipe nobody drains must cost one 2 s grace period and a
    clean skip — not a parked emitter thread (the certifier's StatsEmitter
    true positive: os.write on a blocking pipe ignores the deadline)."""
    import json
    import os

    r, w = os.pipe()
    emitter = sidecar.StatsEmitter(w, interval=60.0)  # thread NOT started
    try:
        # fill the pipe to the last byte so the very first write gets
        # EAGAIN (a partial first write would latch the torn-line arm,
        # which is a different — also bounded — path)
        assert not os.get_blocking(w), (
            "StatsEmitter must flip its fd nonblocking up front; a "
            "blocking pipe makes the 2 s grace period fictional")
        for chunk in (65536, 1):
            while True:
                try:
                    os.write(w, b"x" * chunk)
                except BlockingIOError:
                    break
        result = {}
        t = threading.Thread(
            target=lambda: result.update(
                ok=emitter.dump_once(), took=time.monotonic() - t0),
            daemon=True)
        t0 = time.monotonic()
        t.start()
        t.join(timeout=10)
        assert not t.is_alive(), (
            "dump_once wedged on a full pipe — the grace bound is gone")
        # clean skip: nothing of the record was written, emitter alive
        assert result["ok"] is True
        assert result["took"] < 8
        # the skip must not have latched the emitter dead: drain the
        # filler and the next dump emits a complete JSON line
        os.set_blocking(r, False)
        while True:
            try:
                if not os.read(r, 65536):
                    break
            except BlockingIOError:
                break
        assert emitter.dump_once() is True
        line = b""
        while not line.endswith(b"\n"):
            line += os.read(r, 65536)
        rec = json.loads(line[line.index(b"{"):].decode())
        assert "metrics" in rec
    finally:
        os.close(r)
        os.close(w)


def test_refusal_send_to_wedged_subscriber_is_bounded(monkeypatch):
    """A refusal record sent to a subscriber that never reads must give
    up after _REFUSAL_SEND_TIMEOUT — the accept loop runs refusals
    inline, so an unbounded sendall here wedges admission for every
    later subscriber (the certifier's subscriber-path true positive)."""
    monkeypatch.setattr(sidecar, "_REFUSAL_SEND_TIMEOUT", 0.5)
    a, b = socket.socketpair()
    try:
        # shrink both kernel buffers so a fat record overfills them;
        # b is never read — the classic wedged-peer shape
        a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4096)
        b.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        out = {"type": "refusal", "reason": "fanout_busy",
               "detail": "x" * (1 << 20)}
        t = threading.Thread(
            target=sidecar._send_refusal, args=(a, out), daemon=True)
        t0 = time.monotonic()
        t.start()
        t.join(timeout=10)
        assert not t.is_alive(), (
            "_send_refusal wedged on an unread socket — the send "
            "timeout bound is gone")
        assert time.monotonic() - t0 < 8
    finally:
        a.close()
        b.close()


def test_replica_stats_record_carries_link_age_and_suspicion():
    """The ISSUE 19 satellite: the gossip record snapshot_stats carries
    grows per-peer ``last_success_age_s`` (None until the first
    success, then a growing age — a silently-dead link is an age, not
    a frozen counter) and the node's cumulative ``suspicion``, both
    cross-checked against the driver's own state."""
    from dat_replication_protocol_tpu.cluster import ReplicaNode
    from dat_replication_protocol_tpu.cluster.live import GossipDriver

    node = ReplicaNode("stats-live", ())
    driver = GossipDriver(node, ["127.0.0.1:1", "127.0.0.1:2"],
                          interval=0.05, seed=0)  # never .start()ed
    driver._last_success["127.0.0.1:1"] = time.monotonic() - 2.0
    node._suspect["127.0.0.1:2"] = 3
    sidecar.set_active_gossip(driver)
    try:
        snap = sidecar.snapshot_stats()
        peers = snap["gossip"]["peers"]
        age = peers["127.0.0.1:1"]["last_success_age_s"]
        assert age is not None and 1.9 <= age < 30.0
        assert peers["127.0.0.1:1"]["suspicion"] == 0
        assert peers["127.0.0.1:2"]["last_success_age_s"] is None
        assert peers["127.0.0.1:2"]["suspicion"] == 3
        # ages GROW between snapshots (same driver, no new success)
        snap2 = sidecar.snapshot_stats()
        assert snap2["gossip"]["peers"]["127.0.0.1:1"][
            "last_success_age_s"] >= age
    finally:
        sidecar.set_active_gossip(None)


def test_snapshot_stats_propagation_section_is_presence_gated():
    """The propagation section rides the replica-mode gossip record
    only: an empty board stays OUT (so the fleet's loud-failure rule
    can tell a dark plane from "no exchanges yet"), a populated board
    rides along verbatim."""
    from dat_replication_protocol_tpu.cluster import ReplicaNode
    from dat_replication_protocol_tpu.obs.propagation import PROPAGATION

    PROPAGATION.reset_for_tests()
    sidecar.set_active_gossip(ReplicaNode("stats-prop", ()))
    try:
        snap = sidecar.snapshot_stats()
        assert "gossip" in snap and "propagation" not in snap
        PROPAGATION.record("stats-a", "stats-b", role="initiator",
                           rnd=1, outcome="progress", seconds=0.01,
                           diff=2, repair_bytes=64)
        snap = sidecar.snapshot_stats()
        link = snap["propagation"]["links"]["stats-a->stats-b"]
        assert link["divergence_records"] == 2
        assert link["divergence_bytes"] == 64
        assert snap["propagation"]["exchange_seconds"]["count"] == 1
    finally:
        sidecar.set_active_gossip(None)
        PROPAGATION.reset_for_tests()
