"""The literal sidecar endpoint (round-4 verdict missing #4 / next #6).

The client side of every test is a FOREIGN client: raw wire bytes on a
socket or pipe — no package Encoder — using the hand-derived reference
transcripts from test_wire_fixtures (their wire, reference:
test/basic.js), so these tests prove a non-Python process could pipe
into the TPU data plane exactly the way the reference pipes into a
socket (reference: example.js:53).
"""

import hashlib
import socket
import subprocess
import sys
import threading

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu import sidecar

from test_wire_fixtures import CHANGE_PAYLOAD, SESSION_1, SESSION_4


def _decode_reply(raw: bytes) -> list:
    """Parse the sidecar's reply stream with an independent decoder."""
    out = []
    dec = protocol.decode()
    dec.change(lambda ch, done: (out.append(ch), done()))
    dec.write(raw)
    dec.end()
    assert dec.finished
    return out


def _recv_all(sock: socket.socket) -> bytes:
    parts = []
    while True:
        d = sock.recv(65536)
        if not d:
            return b"".join(parts)
        parts.append(d)


def test_tcp_sidecar_serves_reference_transcript_session_1():
    ready = threading.Event()
    port_box = {}

    def run():
        sidecar.serve_tcp(
            "127.0.0.1", 0, max_sessions=1,
            ready_cb=lambda p: (port_box.__setitem__("p", p), ready.set()),
        )

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert ready.wait(10)
    c = socket.create_connection(("127.0.0.1", port_box["p"]), timeout=10)
    c.sendall(SESSION_1)  # THEIR bytes: one change frame
    c.shutdown(socket.SHUT_WR)
    reply = _decode_reply(_recv_all(c))
    c.close()
    t.join(timeout=10)
    assert len(reply) == 1
    ch = reply[0]
    assert ch.key == "change-0" and ch.subset == "digest:change"
    assert ch.value == hashlib.blake2b(
        CHANGE_PAYLOAD, digest_size=32).digest()


def test_tcp_sidecar_blob_and_change_session_4():
    ready = threading.Event()
    port_box = {}
    t = threading.Thread(
        target=sidecar.serve_tcp,
        args=("127.0.0.1", 0),
        kwargs=dict(max_sessions=1,
                    ready_cb=lambda p: (port_box.__setitem__("p", p),
                                        ready.set())),
        daemon=True,
    )
    t.start()
    assert ready.wait(10)
    c = socket.create_connection(("127.0.0.1", port_box["p"]), timeout=10)
    c.sendall(SESSION_4)  # blob 'hello world' then the parked change
    c.shutdown(socket.SHUT_WR)
    reply = _decode_reply(_recv_all(c))
    c.close()
    by_key = {ch.key: ch for ch in reply}
    assert set(by_key) == {"blob-0", "change-0"}
    assert by_key["blob-0"].value == hashlib.blake2b(
        b"hello world", digest_size=32).digest()
    assert by_key["blob-0"].subset == "digest:blob"
    assert by_key["change-0"].value == hashlib.blake2b(
        CHANGE_PAYLOAD, digest_size=32).digest()


def test_tcp_sidecar_protocol_error_closes_connection():
    ready = threading.Event()
    port_box = {}
    t = threading.Thread(
        target=sidecar.serve_tcp,
        args=("127.0.0.1", 0),
        kwargs=dict(max_sessions=1,
                    ready_cb=lambda p: (port_box.__setitem__("p", p),
                                        ready.set())),
        daemon=True,
    )
    t.start()
    assert ready.wait(10)
    c = socket.create_connection(("127.0.0.1", port_box["p"]), timeout=10)
    c.settimeout(15)
    c.sendall(b"\xff" * 64)  # hostile length varint
    # the sidecar must answer with EOF (destroy cascade), never hang
    assert _recv_all(c) == b""
    c.close()
    t.join(timeout=10)


def test_stdio_sidecar_subprocess_roundtrip():
    """The deployment shape itself: a separate OS process, wire bytes on
    stdin, digest session on stdout."""
    import os

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    # the dev image's sitecustomize re-forces the tunneled platform in
    # fresh interpreters; a wedged tunnel would hang the digest engine's
    # first dispatch.  The routing layer's own override pins the child
    # to the host engine — the test exercises the process boundary and
    # wire contract, not the device.
    env["DAT_DEVICE_HASH"] = "0"
    proc = subprocess.Popen(
        [sys.executable, "-m", "dat_replication_protocol_tpu.sidecar",
         "--stdio", "--backend", "tpu"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        cwd=repo_root, env=env,
    )
    out, err = proc.communicate(SESSION_4, timeout=120)
    assert proc.returncode == 0, err.decode()
    reply = _decode_reply(out)
    assert {ch.key for ch in reply} == {"blob-0", "change-0"}
    assert all(len(ch.value) == 32 for ch in reply)


def test_tcp_sidecar_survives_client_vanishing_mid_reply():
    """A client that closes its whole socket before reading the reply
    must not hang the session thread or crash the daemon (the sender's
    EPIPE tears down both directions)."""
    ready = threading.Event()
    port_box = {}
    t = threading.Thread(
        target=sidecar.serve_tcp,
        args=("127.0.0.1", 0),
        kwargs=dict(max_sessions=1,
                    ready_cb=lambda p: (port_box.__setitem__("p", p),
                                        ready.set())),
        daemon=True,
    )
    t.start()
    assert ready.wait(10)
    c = socket.create_connection(("127.0.0.1", port_box["p"]), timeout=10)
    c.sendall(SESSION_1)
    # vanish entirely: RST-ish close with the reply unread
    c.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                 b"\x01\x00\x00\x00\x00\x00\x00\x00")
    c.close()
    t.join(timeout=30)
    assert not t.is_alive(), "serve loop hung on a vanished client"
