"""The host protocol surface must not initialize a jax backend at import.

Session consumers (decoders in network daemons, CLI tools) import the
package and the runtime helpers; backend initialization at import time
costs seconds always and HANGS when the device tunnel is wedged
(observed).  Device backends must come up lazily at first device use.

(The dev image's sitecustomize preloads the jax *module* into every
interpreter, so the invariant is "no backend init", not "no jax
import".)
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_package_and_runtime_import_without_backend_init():
    code = (
        "import sys\n"
        "import dat_replication_protocol_tpu as protocol\n"
        "from dat_replication_protocol_tpu.runtime import (\n"
        "    TreeSyncSession, content_address, replay_log, tree_sync)\n"
        "from dat_replication_protocol_tpu.session import aio, transport\n"
        "e, d = protocol.encode(), protocol.decode()\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge._backends, (\n"
        "    f'import initialized backends: {list(xla_bridge._backends)}')\n"
        "print('clean')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "clean"
