"""Span layer (ISSUE 4 tentpole): nesting/threading correctness, the
wire-offset frame-tagging contract (sender and receiver compute the
SAME offset for the same frame, and the tags tile the wire with no
gaps on every parse path), Chrome trace export shape, and the
utils.trace JAX-annotation join.
"""

from __future__ import annotations

import json
import threading

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.obs import metrics as obs_metrics
from dat_replication_protocol_tpu.obs import tracing
from dat_replication_protocol_tpu.obs.tracing import (
    SPANS,
    to_chrome_trace,
    trace_instant,
    trace_span,
)
from dat_replication_protocol_tpu.session.resume import WireJournal


# -- span semantics ----------------------------------------------------------


def test_spans_nest_with_parent_links(obs_enabled):
    with trace_span("outer", layer="test"):
        with trace_span("inner"):
            pass
    inner = SPANS.spans("inner")[0]
    outer = SPANS.spans("outer")[0]
    assert inner["parent"] == outer["id"]
    assert outer["parent"] is None
    assert outer["fields"] == {"layer": "test"}
    assert outer["dur"] >= inner["dur"] >= 0.0


def test_instants_inherit_the_enclosing_span(obs_enabled):
    with trace_span("frame-loop"):
        trace_instant("tagged", offset=7)
    tag = SPANS.spans("tagged")[0]
    assert tag["parent"] == SPANS.spans("frame-loop")[0]["id"]
    assert tag["dur"] == 0.0
    assert tag["fields"] == {"offset": 7}


def test_span_records_exception_exit(obs_enabled):
    try:
        with trace_span("doomed"):
            raise ValueError("boom")
    except ValueError:
        pass
    assert SPANS.spans("doomed")[0]["fields"]["error"] == "ValueError"


def test_threads_have_independent_parent_stacks(obs_enabled):
    done = threading.Event()

    def other():
        with trace_span("thread-b"):
            done.wait(5)

    t = threading.Thread(target=other)
    with trace_span("thread-a"):
        t.start()
        while not SPANS.spans():  # wait for b to at least enter
            if not t.is_alive():
                break
        done.set()
        t.join(5)
    b = SPANS.spans("thread-b")[0]
    a = SPANS.spans("thread-a")[0]
    # concurrent spans on different threads must NOT parent each other
    assert b["parent"] is None and a["parent"] is None
    assert b["tid"] != a["tid"]


def test_disabled_gate_records_no_spans():
    assert not obs_metrics.OBS.on
    SPANS.clear()
    with trace_span("dark"):
        pass
    assert SPANS.spans() == []


# -- wire-offset frame tagging -----------------------------------------------


def _build_session():
    """Changes, interleaved corked blobs, a parked change, a multi-KiB
    blob, tails — the PR-2 coverage scenario, journaled for the wire."""
    e = protocol.encode()
    j = WireJournal()
    e.attach_journal(j)
    for i in range(300):  # enough consecutive changes for the C run path
        e.change({"key": f"bulk-{i}", "change": i, "from": i, "to": i + 1,
                  "value": b"v" * (i % 48)})
    b1 = e.blob(11)
    b2 = e.blob(11)
    b1.write(b"hello ")
    b2.write(b"HELLO ")
    b1.write(b"world")
    b2.write(b"WORLD")
    b1.end()
    b2.end()
    big = e.blob(3000)
    big.write(b"x" * 1700)
    e.change({"key": "parked", "change": 99, "from": 0, "to": 1,
              "value": b"after-blob"})
    big.end(b"y" * 1300)
    for i in range(8):
        e.change({"key": f"tail-{i}", "change": i, "from": i, "to": i + 1})
    e.finalize()
    while e.read(4096) is not None:
        pass
    return j.read_from(0)


def _frame_records():
    return [dict(r["fields"], name=r["span"]) for r in SPANS.spans()
            if r.get("span", "").startswith(("encoder.frame",
                                             "decoder.frame"))]


def _assert_tiles(frames, total: int):
    """Frame tags must cover [0, total) contiguously, no overlap."""
    pos = 0
    for f in sorted(frames, key=lambda f: f["offset"]):
        assert f["offset"] == pos, (f, pos)
        pos += f["wire_len"]
    assert pos == total


def test_encoder_frame_tags_tile_the_wire(obs_enabled):
    wire = _build_session()
    frames = [f for f in _frame_records() if f["name"] == "encoder.frame"]
    assert sum(f.get("frames", 1) for f in frames) == 312  # 309 ch + 3 blobs
    _assert_tiles(frames, len(wire))
    # corked blobs were tagged at uncork with their true wire offset
    blob_tags = [f for f in frames if f["kind"] == "blob"]
    assert len(blob_tags) == 3


def test_decoder_frame_tags_agree_with_encoder_on_every_parse_path(
        obs_enabled):
    wire = _build_session()
    enc = {(f["offset"], f["wire_len"]) for f in _frame_records()
           if f["name"] == "encoder.frame"}
    # three chunkings: per-byte straddles (streaming scanner), transport
    # chunks (bulk index + tail scanner), one shot (bulk + C run path)
    for size in (7, 4096, len(wire)):
        SPANS.clear()
        dec = protocol.decode()
        dec.change(lambda c, done: done())
        dec.blob(lambda b, done: b.collect(lambda _d: done()))
        for off in range(0, len(wire), size):
            dec.write(wire[off:off + size])
        dec.end()
        frames = [f for f in _frame_records()
                  if f["name"].startswith("decoder.frame")]
        _assert_tiles(frames, len(wire))
        # every per-frame decoder tag matches a sender tag exactly; run
        # records cover ranges the sender's per-frame tags tile
        for f in frames:
            if f["name"] == "decoder.frame":
                assert (f["offset"], f["wire_len"]) in enc, f
        assert sum(f.get("frames", 1) for f in frames) == 312, size


def _build_batch_session():
    """The negotiated-session variant: ChangeBatch frames + blobs +
    per-record parked tail, journaled for the wire."""
    from dat_replication_protocol_tpu import BatchPolicy, CAP_CHANGE_BATCH

    e = protocol.encode(peer_caps=CAP_CHANGE_BATCH,
                        batch_policy=BatchPolicy(max_rows=64))
    j = WireJournal()
    e.attach_journal(j)
    for i in range(200):
        e.change({"key": f"bulk-{i % 8}", "change": i, "from": i,
                  "to": i + 1, "value": b"v" * (i % 24)})
    b1 = e.blob(11)
    b1.write(b"hello ")
    e.change({"key": "parked", "change": 99, "from": 0, "to": 1})
    b1.end(b"world")
    for i in range(8):
        e.change({"key": f"tail-{i}", "change": i, "from": i, "to": i + 1})
    e.finalize()
    while e.read(4096) is not None:
        pass
    return j.read_from(0)


def test_batch_frame_tags_tile_the_wire_on_both_peers(obs_enabled):
    """ChangeBatch frames carry the same wire-offset causal key as
    per-record frames: encoder tags at emission, decoder tags at
    dispatch, and BOTH tag sets tile the wire on every parse path —
    the timeline contract survives the columnar framing."""
    wire = _build_batch_session()
    enc_frames = [f for f in _frame_records() if f["name"] == "encoder.frame"]
    _assert_tiles(enc_frames, len(wire))
    batch_tags = [f for f in enc_frames if f["kind"] == "change_batch"]
    # 200 rows in 64-row frames (blob flush at 200) + 1 parked per-record
    # + 8 tail rows batched at finalize
    assert len(batch_tags) == 5
    assert sum(f["rows"] for f in batch_tags) == 208
    enc_set = {(f["offset"], f["wire_len"]) for f in enc_frames}
    for size in (7, 4096, len(wire)):
        SPANS.clear()
        dec = protocol.decode()
        dec.change(lambda c, done: done())
        dec.blob(lambda b, done: b.collect(lambda _d: done()))
        for off in range(0, len(wire), size):
            dec.write(wire[off:off + size])
        dec.end()
        assert dec.finished
        frames = [f for f in _frame_records()
                  if f["name"].startswith("decoder.frame")]
        _assert_tiles(frames, len(wire))
        dec_batch = [f for f in frames if f.get("kind") == "change_batch"]
        assert len(dec_batch) == 5 and sum(
            f["rows"] for f in dec_batch) == 208, size
        for f in frames:
            if f["name"] == "decoder.frame":
                assert (f["offset"], f["wire_len"]) in enc_set, (size, f)


def test_frame_offsets_stay_absolute_across_resume(obs_enabled):
    """A decoder that survives a mid-session fault keeps counting wire
    offsets absolutely — resumed frames tag where they truly live."""
    from dat_replication_protocol_tpu.session.faults import (
        FaultPlan,
        FaultyReader,
        bytes_reader,
    )
    from dat_replication_protocol_tpu.session.reconnect import (
        BackoffPolicy,
        run_resumable,
    )

    wire = _build_session()
    SPANS.clear()
    dec = protocol.decode()
    dec.change(lambda c, done: done())
    dec.blob(lambda b, done: b.collect(lambda _d: done()))

    def source(ckpt, failures):
        plan = FaultPlan(
            seed=failures, max_segment=64,
            drop_at=(len(wire) // 2 - ckpt.wire_offset)
            if failures == 0 else None)
        return FaultyReader(bytes_reader(wire[ckpt.wire_offset:]), plan)

    stats = run_resumable(source, dec,
                          BackoffPolicy(base=0.0, max_retries=3, seed=0),
                          expected_total=len(wire))
    assert stats["reconnects"] == 1
    frames = [f for f in _frame_records()
              if f["name"].startswith("decoder.frame")]
    # no duplicate deliveries, no gaps: the tags still tile the wire
    _assert_tiles(frames, len(wire))
    # and the reconnect attempts left spans keyed on their resume offset
    attempts = SPANS.spans("reconnect.attempt")
    assert [s["fields"]["attempt"] for s in attempts] == [1, 2]
    assert attempts[1]["fields"]["offset"] > 0


# -- Chrome trace export -----------------------------------------------------


def test_chrome_trace_export_shape(obs_enabled, tmp_path):
    with trace_span("phase", offset=0):
        trace_instant("tick", offset=10)
    obs_metrics.REGISTRY.counter("x.y")  # registry noise must not leak in
    from dat_replication_protocol_tpu.obs.events import emit

    emit("some.event", offset=4)
    doc = to_chrome_trace()
    names = {ev["name"] for ev in doc["traceEvents"]}
    assert {"phase", "tick", "some.event"} <= names
    for ev in doc["traceEvents"]:
        assert ev["ph"] in ("X", "i")
        assert isinstance(ev["ts"], float)
        if ev["ph"] == "X":
            assert ev["dur"] >= 0.0
        else:
            assert ev["s"] in ("t", "p")
    # timestamps sorted (viewers tolerate unsorted, humans diffing don't)
    ts = [ev["ts"] for ev in doc["traceEvents"]]
    assert ts == sorted(ts)
    out = tracing.export_chrome_trace(str(tmp_path / "t.json"))
    assert json.load(open(out))["traceEvents"]


def test_utils_trace_span_joins_the_obs_ring(obs_enabled):
    from dat_replication_protocol_tpu.utils.trace import span

    with span("jax-phase"):
        pass
    rec = SPANS.spans("jax-phase")
    assert len(rec) == 1 and rec[0]["fields"]["src"] == "jax"


def test_utils_trace_joined_span_unwinds_on_inner_enter_raise(obs_enabled):
    """If the jax annotation's __enter__ raises, the obs span must pop
    its id off the threadlocal parent stack — a leaked id would corrupt
    every later span's parent link on this thread."""
    from dat_replication_protocol_tpu.utils.trace import _JoinedSpan

    class ExplodingInner:
        def __enter__(self):
            raise RuntimeError("profiler in a bad state")

        def __exit__(self, *exc):
            return False

    with pytest.raises(RuntimeError):
        with _JoinedSpan("doomed-jax", ExplodingInner()):
            raise AssertionError("body must not run")
    assert tracing._stack() == []  # nothing leaked
    with trace_span("clean-after"):
        pass
    assert SPANS.spans("clean-after")[0]["parent"] is None


def test_utils_trace_span_unchanged_when_gate_off():
    from dat_replication_protocol_tpu.utils import trace

    assert not obs_metrics.OBS.on
    SPANS.clear()
    with trace.span("dark-jax"):
        pass
    assert SPANS.spans() == []


def test_jsonl_sink_mirrors_spans_and_events_one_object_per_line(
        obs_enabled, tmp_path):
    from dat_replication_protocol_tpu.obs.events import EVENTS, emit

    path = tmp_path / "peer.jsonl"
    sink = tracing.attach_jsonl_sink(str(path))
    try:
        with trace_span("mirrored"):
            emit("mirrored.event", offset=1)
    finally:
        EVENTS.detach_sink()
        SPANS.detach_sink()
        sink.close()
    records = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert {r.get("span") or r.get("event") for r in records} == {
        "mirrored", "mirrored.event"}
