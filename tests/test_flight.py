"""Flight recorder (ISSUE 4 tentpole): atomic post-mortem bundles, and
the chaos-sweep attribution oracle — for every seed in the 20-seed
``FaultPlan.for_sweep`` run, the injected fault's coordinates (kind,
wire offset) must be recoverable from the flight bundle ALONE: the
assertions below read nothing but the files inside the bundle
directory.
"""

from __future__ import annotations

import os

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.obs import flight
from dat_replication_protocol_tpu.obs import metrics as obs_metrics
from dat_replication_protocol_tpu.session.faults import (
    FaultPlan,
    FaultyReader,
    bytes_reader,
)
from dat_replication_protocol_tpu.session.reconnect import (
    BackoffPolicy,
    retrying,
    run_resumable,
)
from dat_replication_protocol_tpu.session.resume import WireJournal
from dat_replication_protocol_tpu.wire.framing import ProtocolError

FLIGHT = flight.FLIGHT


def _build_wire() -> bytes:
    e = protocol.encode()
    j = WireJournal()
    e.attach_journal(j)
    for i in range(24):
        e.change({"key": f"bulk-{i}", "change": i, "from": i, "to": i + 1,
                  "value": b"v%03d" % i})
    big = e.blob(3000)
    big.write(b"x" * 1700)
    e.change({"key": "parked", "change": 99, "from": 0, "to": 1,
              "value": b"after-blob"})
    big.end(b"y" * 1300)
    for i in range(8):
        e.change({"key": f"tail-{i}", "change": i, "from": i, "to": i + 1})
    e.finalize()
    while e.read(4096) is not None:
        pass
    return j.read_from(0)


_WIRE = _build_wire()


def _plan_kind(plan: FaultPlan) -> str | None:
    if plan.drop_at is not None:
        return "drop"
    if plan.truncate_at is not None:
        return "truncate"
    if plan.stall_at is not None:
        return "stall"
    if plan.max_segment == 1:
        return "reseg"
    return None


def _run_sweep_seed(seed: int):
    """One conformance-sweep seed under an armed recorder; returns the
    ground-truth plans + per-connection start offsets."""
    dec = protocol.decode()
    dec.change(lambda c, done: done())
    dec.blob(lambda b, done: b.collect(lambda _d: done()))
    plans: list[FaultPlan] = []
    offsets: list[int] = []

    def source(ckpt, failures):
        offsets.append(ckpt.wire_offset)
        replay = _WIRE[ckpt.wire_offset:]
        plan = FaultPlan.for_sweep(seed, len(replay), attempt=failures)
        plans.append(plan)
        return FaultyReader(bytes_reader(replay), plan)

    stats = run_resumable(
        source, dec,
        BackoffPolicy(base=0.0005, cap=0.005, max_retries=8, seed=seed,
                      sleep=lambda _d: None),
        chunk_size=1024, expected_total=len(_WIRE), stall_timeout=15)
    assert dec.finished and dec.changes == 33
    return stats, plans, offsets


def test_sweep_every_fault_attributable_from_bundle_alone(
        obs_enabled, tmp_path):
    """The acceptance criterion: 20 seeds, each fault's (kind, wire
    offset) recovered from the bundle files alone."""
    kinds_seen: set[str] = set()
    for seed in range(20):
        obs_metrics.REGISTRY.reset()
        from dat_replication_protocol_tpu.obs.events import EVENTS
        from dat_replication_protocol_tpu.obs.tracing import SPANS

        EVENTS.clear()
        SPANS.clear()
        FLIGHT._reset_for_tests()
        FLIGHT.arm(str(tmp_path / f"seed-{seed}"), enable_telemetry=False)
        stats, plans, offsets = _run_sweep_seed(seed)
        if FLIGHT.last_bundle is None:
            # a seed whose faults are all absorbed without a transport
            # fault (reseg/stall class) leaves no automatic incident
            # bundle — the operator's explicit dump is the same bundle
            flight.dump("sweep-complete")
        bundle = flight.read_bundle(FLIGHT.last_bundle)
        events = bundle["events"]
        counters = bundle["metrics"]["counters"]
        recorded_plans = bundle["manifest"]["fault_plans"]
        ctx = f"seed {seed}"
        for plan, conn_off in zip(plans, offsets):
            kind = _plan_kind(plan)
            if kind is None:
                continue
            kinds_seen.add(kind)
            # the plan itself (seed + coordinates) rides in the manifest
            assert any(p["seed"] == plan.seed for p in recorded_plans), ctx
            if kind == "drop":
                # absolute wire offset = connection start + plan offset
                want = conn_off + plan.drop_at
                assert any(e.get("event") == "fault.drop"
                           and conn_off + e["fields"]["offset"] == want
                           for e in events), ctx
            elif kind == "truncate":
                want = conn_off + plan.truncate_at
                assert any(e.get("event") == "fault.truncate"
                           and conn_off + e["fields"]["offset"] == want
                           for e in events), ctx
            elif kind == "stall":
                assert any(e.get("event") == "fault.stall"
                           and e["fields"]["seconds"] == plan.stall_s
                           for e in events), ctx
            elif kind == "reseg":
                assert counters.get(
                    "fault.injected.reseg_segments", 0) > 0, ctx
        # the bundle's session narrative agrees with the driver
        assert sum(1 for e in events
                   if e.get("event") == "reconnect.fault") == len(
                       stats["faults"]), ctx
    assert kinds_seen == {"drop", "truncate", "stall", "reseg"}, kinds_seen


def test_recovered_session_dumps_incident_bundle(obs_enabled, tmp_path):
    FLIGHT.arm(str(tmp_path))
    dec = protocol.decode()
    dec.change(lambda c, done: done())
    dec.blob(lambda b, done: b.collect(lambda _d: done()))

    def source(ckpt, failures):
        plan = FaultPlan(seed=failures,
                         drop_at=(50 if failures == 0 else None))
        return FaultyReader(bytes_reader(_WIRE[ckpt.wire_offset:]), plan)

    stats = run_resumable(source, dec,
                          BackoffPolicy(base=0, max_retries=3, seed=0),
                          expected_total=len(_WIRE))
    assert stats["reconnects"] == 1
    names = sorted(os.listdir(tmp_path))
    assert len(names) == 1 and "recovered" in names[0]
    assert not any(n.startswith(".tmp") for n in names)  # atomic rename
    b = flight.read_bundle(os.path.join(tmp_path, names[0]))
    assert b["manifest"]["extra"]["stats"]["reconnects"] == 1
    assert any(e.get("event") == "fault.drop"
               and e["fields"]["offset"] == 50 for e in b["events"])


def test_reconnect_exhaustion_dumps_bundle_with_checkpoint(
        obs_enabled, tmp_path):
    FLIGHT.arm(str(tmp_path))
    dec = protocol.decode()
    dec.change(lambda c, done: done())

    def source(ckpt, failures):
        return FaultyReader(bytes_reader(_WIRE[ckpt.wire_offset:]),
                            FaultPlan(seed=0, drop_at=10))

    with pytest.raises(ProtocolError) as ei:
        run_resumable(source, dec,
                      BackoffPolicy(base=0, max_retries=1, seed=0),
                      expected_total=len(_WIRE))
    names = os.listdir(tmp_path)
    assert len(names) == 1 and "session-failed" in names[0]
    b = flight.read_bundle(os.path.join(tmp_path, names[0]))
    err = b["manifest"]["error"]
    assert err["type"] == "ProtocolError"
    assert err["offset"] == ei.value.offset
    assert err["frame"] == ei.value.frame
    # the checkpoint a resume WOULD have used rides along
    assert b["manifest"]["checkpoint"]["wire_offset"] == dec.bytes


def test_protocol_error_dumps_one_bundle_despite_reraise(
        obs_enabled, tmp_path):
    """The decoder dumps at _protocol_error; run_resumable re-raises
    the SAME object — identity dedup keeps it to one bundle."""
    FLIGHT.arm(str(tmp_path))
    dec = protocol.decode()

    def source(ckpt, failures):
        return FaultyReader(bytes_reader(_WIRE[ckpt.wire_offset:]),
                            FaultPlan(seed=0, flip_at=1, flip_mask=0x44))

    with pytest.raises(ProtocolError):
        run_resumable(source, dec,
                      BackoffPolicy(base=0, max_retries=1, seed=0),
                      expected_total=len(_WIRE))
    names = os.listdir(tmp_path)
    assert len(names) == 1 and "protocol-error" in names[0], names
    assert FLIGHT.suppressed >= 1
    b = flight.read_bundle(os.path.join(tmp_path, names[0]))
    # the flip is in the bundle's events; the error coordinates are in
    # its manifest — attribution needs nothing else
    assert any(e.get("event") == "fault.flip" for e in b["events"])
    assert b["manifest"]["error"]["offset"] is not None


def test_retrying_exhaustion_dumps_bundle(obs_enabled, tmp_path):
    FLIGHT.arm(str(tmp_path))

    def always_fails():
        raise OSError("bind refused")

    with pytest.raises(ProtocolError):
        retrying(always_fails, BackoffPolicy(base=0, max_retries=1, seed=0),
                 describe="bind")
    names = os.listdir(tmp_path)
    assert len(names) == 1 and "retry-exhausted" in names[0]
    b = flight.read_bundle(os.path.join(tmp_path, names[0]))
    assert "bind" in b["manifest"]["error"]["message"]


def test_bundle_budget_bounds_an_error_storm(obs_enabled, tmp_path):
    FLIGHT.arm(str(tmp_path), max_bundles=2)
    for i in range(5):
        dec = protocol.decode()
        dec.on_error(lambda _e: None)
        dec.write(b"\x05\x09zzzz")  # unknown type id 9 -> destroy
        assert dec.destroyed
    names = [n for n in os.listdir(tmp_path) if not n.startswith(".")]
    assert len(names) == 2
    assert FLIGHT.suppressed == 3


def test_routine_dumps_cannot_starve_failure_bundles(obs_enabled, tmp_path):
    """Recovered-session dumps are routine: capped at half the budget,
    so a long-lived process absorbing transient faults always has
    bundles left for a genuine failure's post-mortem."""
    FLIGHT.arm(str(tmp_path), max_bundles=4)
    for i in range(5):
        flight.dump("recovered", routine=True)
    names = [n for n in os.listdir(tmp_path) if not n.startswith(".")]
    assert len(names) == 2  # half of 4
    # a failure dump still lands
    assert flight.dump("session-failed",
                       error=ProtocolError("boom", offset=1)) is not None
    assert len([n for n in os.listdir(tmp_path)
                if "session-failed" in n]) == 1


def test_rearming_resets_the_dump_budget_and_dedup(obs_enabled, tmp_path):
    """arm() is a fresh capture: a recorder that spent its budget (or
    bundled an error) must not stay silently suppressed after re-arm."""
    FLIGHT.arm(str(tmp_path / "a"), max_bundles=1)
    err = None
    dec = protocol.decode()
    dec.on_error(lambda e: None)
    dec.write(b"\x05\x09zzzz")
    assert flight.dump("over-budget") is None  # budget of 1 is spent
    assert FLIGHT.suppressed == 1
    FLIGHT.arm(str(tmp_path / "b"), max_bundles=1)
    assert FLIGHT.suppressed == 0
    assert flight.dump("fresh-capture") is not None
    assert os.listdir(tmp_path / "b")
    assert err is None


def test_rearming_the_same_directory_never_collides_bundle_names(
        obs_enabled, tmp_path):
    """Bundle names carry a per-arm capture generation: re-arming into
    the SAME directory must not collide with (and silently lose) a new
    incident whose (seq, reason) repeats a previous capture's."""
    FLIGHT.arm(str(tmp_path))
    assert flight.dump("protocol-error",
                       error=ProtocolError("one", offset=1)) is not None
    FLIGHT.arm(str(tmp_path))  # same dir, fresh capture
    second = flight.dump("protocol-error",
                         error=ProtocolError("two", offset=2))
    assert second is not None, "second capture's bundle was lost"
    names = [n for n in os.listdir(tmp_path) if not n.startswith(".")]
    assert len(names) == 2
    assert flight.read_bundle(second)["manifest"]["error"]["offset"] == 2


def test_flight_checkpoint_context_emits_no_checkpoint_event(
        obs_enabled, tmp_path):
    """The checkpoint a bundle carries is CONTEXT, not a resume point:
    dumping must not append session.checkpoint to the event stream."""
    from dat_replication_protocol_tpu.obs.events import EVENTS

    FLIGHT.arm(str(tmp_path))
    dec = protocol.decode()
    dec.on_error(lambda _e: None)
    dec.write(b"\x05\x09zzzz")
    assert dec.destroyed and FLIGHT.last_bundle is not None
    assert EVENTS.count("session.checkpoint") == 0
    assert EVENTS.count("protocol.error") == 1
    # but the bundle still carries the checkpoint fields
    b = flight.read_bundle(FLIGHT.last_bundle)
    assert b["manifest"]["checkpoint"]["wire_offset"] == dec.bytes


def test_disarmed_recorder_dumps_nothing(obs_enabled, tmp_path):
    assert not FLIGHT.armed
    dec = protocol.decode()
    dec.on_error(lambda _e: None)
    dec.write(b"\x05\x09zzzz")
    assert dec.destroyed
    assert FLIGHT.last_bundle is None
    assert flight.dump("manual") is None
