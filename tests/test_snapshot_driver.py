"""Snapshot bootstrap driver (ISSUE 12): the protocol cores and live
duplex drivers.  The claims under test are the tentpole's economics and
failure contract:

* a 2% stale joiner moves ~2% of the bytes (O(diff) wire via the
  weighted rateless reconcile), a cold joiner takes the full-manifest
  fallback, an identical joiner moves almost nothing;
* a flash crowd of cold joiners shares ONE hash+read+encode pass
  (hash-once counters: ``cdc.fused.bytes`` flat as joiners grow);
* every chunk digest is verified on receipt; a wrong chunk, an
  unsolicited chunk, a bad assembly plan, or an over-budget session is
  ONE structured ProtocolError — never a silently wrong dataset.
"""

from __future__ import annotations

import socket
import threading

import numpy as np
import pytest

from dat_replication_protocol_tpu.runtime.snapshot_driver import (
    SnapshotJoiner,
    SnapshotResponder,
    SnapshotSource,
    run_snapshot_joiner,
    run_snapshot_responder,
    snapshot_local,
    symbol_cap,
)
from dat_replication_protocol_tpu.wire import snapshot_codec as sn
from dat_replication_protocol_tpu.wire.framing import ProtocolError


def _dataset(n: int, seed: int = 0) -> np.ndarray:
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8)


def _stale_copy(data: np.ndarray, frac: float, seed: int = 1) -> bytes:
    """Corrupt ~frac of the CHUNKS by flipping one byte in each: the
    divergence is chunk-count-shaped, like a real stale replica."""
    src = SnapshotSource(data)
    rng = np.random.default_rng(seed)
    n = len(src.offs)
    pick = rng.choice(n, size=max(1, int(n * frac)), replace=False)
    out = data.copy()
    out[src.offs[pick]] ^= 0x5A
    return out.tobytes()


DATA = _dataset(1 << 20)
SRC = SnapshotSource(DATA, wire_offset=4242)


def test_cold_joiner_full_manifest_fallback():
    out = snapshot_local(SRC, None)
    assert out["data"] == DATA.tobytes()
    assert out["cold"] is True
    assert out["symbols"] == 0  # no symbol stream on the cold path
    assert out["chunks_received"] == SRC.manifest.n_chunks
    assert out["wire_offset"] == 4242  # where the live session attaches


def test_stale_joiner_wire_scales_with_staleness():
    stale = _stale_copy(DATA, 0.02)
    cold = snapshot_local(SRC, None)
    out = snapshot_local(SRC, stale)
    assert out["data"] == DATA.tobytes()
    assert not out["cold"]
    assert out["chunks_reused"] > 0
    # the acceptance shape: 2% stale moves <= 5% of the cold transfer
    assert out["wire_bytes"] <= 0.05 * cold["wire_bytes"], (
        out["wire_bytes"], cold["wire_bytes"])


def test_identical_joiner_moves_no_chunk_bytes():
    out = snapshot_local(SRC, DATA.tobytes())
    assert out["data"] == DATA.tobytes()
    assert out["bytes_received"] == 0
    assert out["chunks_received"] == 0
    # manifest + symbols + empty WANT/DONE only: well under 1% of data
    assert out["wire_bytes"] < len(DATA) // 100


def test_repeating_content_dedupes_positions():
    # 64 copies of one 16 KiB block: many positions, few unique chunks,
    # and the DONE assembly plan must reconstruct the repetition
    block = _dataset(16 << 10, seed=3)
    data = np.tile(block, 64)
    src = SnapshotSource(data)
    assert src.manifest.n_chunks < src.manifest.n_positions
    out = snapshot_local(src, None)
    assert out["data"] == data.tobytes()
    assert out["chunks_received"] == src.manifest.n_chunks  # each once


def test_empty_dataset_roundtrips():
    src = SnapshotSource(np.empty(0, np.uint8))
    out = snapshot_local(src, None)
    assert out["data"] == b""


def test_flash_crowd_shares_one_hash_pass(obs_enabled):
    from dat_replication_protocol_tpu.obs.metrics import REGISTRY

    data = _dataset(1 << 19, seed=5)
    src = SnapshotSource(data)  # the hash pass (counted)
    hashed_once = REGISTRY.counter("cdc.fused.bytes").value
    sent0 = REGISTRY.counter("snapshot.chunks.sent_bytes").value
    for _ in range(4):  # the crowd
        out = snapshot_local(src, None)
        assert out["data"] == data.tobytes()
    # digest work did NOT grow with joiners (hash_ratio 1.0) ...
    assert REGISTRY.counter("cdc.fused.bytes").value == hashed_once
    # ... while the bytes served DID
    sent = REGISTRY.counter("snapshot.chunks.sent_bytes").value - sent0
    assert sent >= 4 * len(data)
    # and the shared cold log was framed once, not per joiner
    assert src._cold_log is not None


def test_chunk_budget_fails_structured():
    resp = SnapshotResponder(SRC, chunk_budget=1024)
    [begin] = resp.begin_payloads()
    replies = resp.handle(sn.decode_snapshot(sn.encode_want_all()))
    assert len(replies) == 1
    msg = sn.decode_snapshot(replies[0])
    assert msg.kind == sn.SN_FAIL and "budget" in msg.reason
    assert isinstance(resp.failed, ProtocolError)
    # and the joiner surfaces it as ITS one structured error
    joiner = SnapshotJoiner(None)
    joiner.handle(sn.decode_snapshot(sn.encode_begin(SRC.manifest)))
    joiner.handle(msg)
    with pytest.raises(ProtocolError, match="budget"):
        joiner.result()


def test_flipped_chunk_is_one_structured_error():
    joiner = SnapshotJoiner(None)
    joiner.handle(sn.decode_snapshot(sn.encode_begin(SRC.manifest)))
    good = SRC.chunk_view(0).tobytes()
    bad = bytes([good[0] ^ 1]) + good[1:]
    replies = joiner.handle(sn.decode_snapshot(sn.encode_chunks(
        [(SRC.uniq_digests[0].tobytes(), bad)])))
    assert sn.decode_snapshot(replies[0]).kind == sn.SN_FAIL
    with pytest.raises(ProtocolError, match="digest mismatch"):
        joiner.result()


def test_unsolicited_chunk_outside_want_set_errors():
    stale = _stale_copy(DATA, 0.02)
    # drive the joiner through reconcile so it HAS a WANT set, then
    # deliver a chunk it never asked for (valid digest, wrong session)
    resp = SnapshotResponder(SRC)
    joiner = SnapshotJoiner(stale)
    pending = [p for p in resp.begin_payloads()]
    for _ in range(100):
        replies = []
        for p in pending:
            replies.extend(joiner.handle(sn.decode_snapshot(p)))
        if joiner._wanted is not None:
            break
        pending = []
        for r in replies:
            pending.extend(resp.handle(sn.decode_snapshot(r)))
    assert joiner._wanted is not None
    outside = [u for u in range(SRC.manifest.n_chunks)
               if SRC.uniq_digests[u].tobytes() not in joiner._wanted]
    u = outside[0]
    joiner.handle(sn.decode_snapshot(sn.encode_chunks(
        [(SRC.uniq_digests[u].tobytes(), SRC.chunk_view(u).tobytes())])))
    with pytest.raises(ProtocolError, match="unsolicited"):
        joiner.result()


def test_done_with_undelivered_chunks_errors():
    joiner = SnapshotJoiner(_stale_copy(DATA, 0.02))
    joiner.handle(sn.decode_snapshot(sn.encode_begin(SRC.manifest)))
    # skip straight to DONE without delivering the wanted chunks
    joiner._wanted = {SRC.uniq_digests[0].tobytes(): 1}
    joiner.handle(sn.decode_snapshot(SRC.done_payload(0)))
    with pytest.raises(ProtocolError, match="undelivered"):
        joiner.result()


def test_bad_assembly_plan_fails_root_check():
    # cold transfer with a shuffled DONE plan: every chunk verifies,
    # but the ROOT over the per-position digests must refuse the order
    src = SnapshotSource(_dataset(1 << 17, seed=9))
    if src.manifest.n_positions < 2:
        pytest.skip("dataset chunked to fewer than 2 positions")
    joiner = SnapshotJoiner(None)
    joiner.handle(sn.decode_snapshot(sn.encode_begin(src.manifest)))
    chunks = [(src.uniq_digests[u].tobytes(), src.chunk_view(u).tobytes())
              for u in range(src.manifest.n_chunks)]
    joiner.handle(sn.decode_snapshot(sn.encode_chunks(chunks)))
    ranks = src.ranks.copy()
    ranks[0], ranks[-1] = ranks[-1], ranks[0]
    if ranks[0] == ranks[-1]:
        pytest.skip("degenerate: swapped positions share a chunk")
    joiner.handle(sn.decode_snapshot(sn.encode_done(0, ranks)))
    with pytest.raises(ProtocolError, match="root"):
        joiner.result()


def test_stream_ending_before_assembly_is_structured():
    joiner = SnapshotJoiner(None)
    joiner.handle(sn.decode_snapshot(sn.encode_begin(SRC.manifest)))
    with pytest.raises(ProtocolError, match="before assembly"):
        joiner.result()


def test_redelivered_chunk_absorbed_exactly_once():
    # the exactly-once contract's unit face: the same CHUNKS frame
    # twice verifies (and counts) each chunk once
    src = SnapshotSource(_dataset(1 << 16, seed=11))
    joiner = SnapshotJoiner(None)
    joiner.handle(sn.decode_snapshot(sn.encode_begin(src.manifest)))
    payload = sn.encode_chunks(
        [(src.uniq_digests[u].tobytes(), src.chunk_view(u).tobytes())
         for u in range(src.manifest.n_chunks)])
    joiner.handle(sn.decode_snapshot(payload))
    before = joiner.chunks_verified
    joiner.handle(sn.decode_snapshot(payload))  # the replay
    assert joiner.chunks_verified == before  # absorbed, not re-counted
    joiner.handle(sn.decode_snapshot(src.done_payload(0)))
    assert joiner.result()["data"] == src._buf.tobytes()


def _divergent_pair():
    """A small manifest vs a joiner whose local set dwarfs it: the
    symmetric difference (~1k chunks) cannot decode under the manifest
    cap (512 symbols for ~32 source chunks)."""
    small = _dataset(1 << 18, seed=21)
    have = _dataset(1 << 23, seed=22).tobytes()  # unrelated content
    return SnapshotSource(small), small, have


def test_heavily_divergent_joiner_degrades_to_want_all():
    # the joiner mirrors symbol_cap(n_chunks) and degrades to the
    # full-manifest WANT before the responder refuses a batch; the
    # pre-fix joiner waited for its own max_symbols (1<<20), which the
    # responder's smaller cap always preempted with FAIL — the
    # documented degrade path was unreachable and the session stranded
    src, small, have = _divergent_pair()
    assert symbol_cap(src.manifest.n_chunks) < SnapshotJoiner(None).max_symbols
    out = snapshot_local(src, have)
    assert out["data"] == small.tobytes()
    assert out["cold"] is True  # degraded to the full-manifest path
    assert out["symbols"] > 0  # only after the reconcile was tried


def test_divergent_joiner_without_fallback_fails_structured():
    # fallback_all=False keeps the strict contract: the same exhaustion
    # is ONE structured error originated by the JOINER, not a responder
    # refusal racing it
    src, _small, have = _divergent_pair()
    resp = SnapshotResponder(src)
    joiner = SnapshotJoiner(have, fallback_all=False)
    pending = list(resp.begin_payloads())
    while pending and not joiner.done:
        replies = []
        for p in pending:
            replies.extend(joiner.handle(sn.decode_snapshot(p)))
        pending = []
        for r in replies:
            pending.extend(resp.handle(sn.decode_snapshot(r)))
    with pytest.raises(ProtocolError, match="no decode after"):
        joiner.result()
    # the responder learned of it from the joiner's FAIL — it never
    # originated a cap refusal of its own
    assert "at joiner" in str(resp.failed)


def test_want_digests_repeats_served_once():
    # WANT is semantically a set: a byzantine joiner repeating one
    # digest k times must not amplify the reply (pre-fix each repeat
    # shipped another copy of the chunk, unbounded on the sidecar path
    # where chunk_budget is never set)
    resp = SnapshotResponder(SRC)
    resp.begin_payloads()
    d = SRC.uniq_digests[0].tobytes()
    want = np.frombuffer(d * 64, np.uint8).reshape(64, 32).copy()
    replies = resp.handle(sn.decode_snapshot(sn.encode_want_digests(want)))
    msgs = [sn.decode_snapshot(r) for r in replies]
    chunks = [c for m in msgs if m.kind == sn.SN_CHUNKS for c in m.chunks]
    assert len(chunks) == 1  # the chunk once, then DONE
    assert resp.chunks_sent == 1
    assert resp.chunk_bytes_sent == int(SRC.uniq_lens[0])


def test_done_payload_caches_the_ranks_tail():
    # the ranks blob is constant per manifest: encoded once, shared by
    # every session's DONE (only the symbols_used prefix varies)
    src = SnapshotSource(_dataset(1 << 17, seed=13))
    a = src.done_payload(3)
    assert src._done_tail is not None
    b = src.done_payload(9)
    assert a == sn.encode_done(3, src.ranks)
    assert b == sn.encode_done(9, src.ranks)


# -- live duplex drivers -----------------------------------------------------


def _run_live(have, *, chunk_budget=None):
    a, b = socket.socketpair()
    res: dict = {}

    def respond():
        try:
            res["resp"] = run_snapshot_responder(
                SRC, a.recv, a.sendall,
                lambda: a.shutdown(socket.SHUT_WR),
                chunk_budget=chunk_budget)
        except ProtocolError as e:
            res["resp_err"] = e

    t = threading.Thread(target=respond, daemon=True)
    t.start()
    try:
        out = run_snapshot_joiner(
            b.recv, b.sendall, lambda: b.shutdown(socket.SHUT_WR),
            have=have)
    finally:
        t.join(timeout=30)
        a.close()
        b.close()
    assert not t.is_alive()
    return out, res


def test_live_cold_join_over_socketpair():
    out, res = _run_live(None)
    assert out["data"] == DATA.tobytes()
    assert out["wire_offset"] == 4242
    assert res["resp"]["cold"] is True


def test_live_stale_join_over_socketpair():
    out, res = _run_live(_stale_copy(DATA, 0.02))
    assert out["data"] == DATA.tobytes()
    assert out["chunks_reused"] > 0
    assert res["resp"]["ok"] is True
    # chunk bytes on the wire tracked the diff, not the dataset
    assert out["bytes_received"] < len(DATA) // 4


def test_live_budget_fail_is_structured_on_both_sides():
    out_err = None
    a, b = socket.socketpair()
    res: dict = {}

    def respond():
        try:
            run_snapshot_responder(
                SRC, a.recv, a.sendall,
                lambda: a.shutdown(socket.SHUT_WR), chunk_budget=1024)
        except ProtocolError as e:
            res["err"] = e

    t = threading.Thread(target=respond, daemon=True)
    t.start()
    try:
        run_snapshot_joiner(b.recv, b.sendall,
                            lambda: b.shutdown(socket.SHUT_WR), have=None)
    except ProtocolError as e:
        out_err = e
    finally:
        t.join(timeout=30)
        a.close()
        b.close()
    assert out_err is not None and "budget" in str(out_err)
    assert isinstance(res.get("err"), ProtocolError)


def test_watermark_roles_ride_the_fleet_plane(obs_enabled):
    from dat_replication_protocol_tpu.obs.watermarks import WATERMARKS

    a, b = socket.socketpair()

    def respond():
        run_snapshot_responder(
            SRC, a.recv, a.sendall, lambda: a.shutdown(socket.SHUT_WR),
            link="snap-test-resp")

    t = threading.Thread(target=respond, daemon=True)
    t.start()
    try:
        out = run_snapshot_joiner(
            b.recv, b.sendall, lambda: b.shutdown(socket.SHUT_WR),
            have=None, link="snap-test-join")
    finally:
        t.join(timeout=30)
        a.close()
        b.close()
    assert out["data"] == DATA.tobytes()
    # roles untracked after the sessions closed (no leaked links)
    snap = WATERMARKS.snapshot()
    assert "snap-test-resp" not in snap and "snap-test-join" not in snap


def test_assembly_ranks_match_lex_order_reference():
    """The vectorized rank build (np.unique inverse over the V32 void
    view) must equal the definitional reference: each position's rank
    in the byte-lexicographically sorted unique digest set."""
    from dat_replication_protocol_tpu.ops.rateless import dedupe_digests
    from dat_replication_protocol_tpu.runtime.snapshot_driver import (
        _lex_order,
    )

    block = _dataset(16 << 10, seed=21)
    src = SnapshotSource(np.tile(block, 16))  # repeats => duplicates
    uniq, _ = dedupe_digests(src.digests)
    order = _lex_order(uniq)
    rank_of = np.empty(len(order), np.int64)
    rank_of[order] = np.arange(len(order), dtype=np.int64)
    by = {uniq[i].tobytes(): i for i in range(len(uniq))}
    ref = np.array([rank_of[by[src.digests[p].tobytes()]]
                    for p in range(len(src.digests))], dtype=np.int64)
    assert np.array_equal(src.ranks, ref)


def test_shared_weighted_symbols_concurrent_extend_is_exact():
    """The per-manifest symbol prefix is SHARED across concurrent
    responder sessions: racing extend() calls must serialize on the
    in-place cursor and every thread must observe exactly the
    single-threaded prefix (a torn cursor builds cells that never
    peel — the route-fork failure class)."""
    from dat_replication_protocol_tpu.ops import rateless

    d = _dataset(1 << 16, seed=23)
    src = SnapshotSource(d)
    ref = rateless.WeightedSymbols(
        src.uniq_digests, src.uniq_lens).extend(512).copy()
    ws = src.weighted_symbols()
    out, errs = {}, []

    def worker(i):
        try:
            out[i] = np.asarray(ws.extend(512)).copy()
        except Exception as e:  # noqa: BLE001 — relayed to the assert
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errs
    for i, cells in out.items():
        assert cells.tobytes() == ref.tobytes(), f"thread {i} diverged"


# -- review round 2: budget accounting + cold-pump pacing ---------------------


def test_want_all_budget_bills_unique_bytes_not_position_total():
    """The cold log ships each UNIQUE chunk once; the budget guard and
    the sent counters must bill what actually moves.  A tiled dataset
    (total_bytes ~64x the unique bytes) whose unique set fits the
    budget must NOT be spuriously FAILed."""
    block = _dataset(16 << 10, seed=11)
    data = np.tile(block, 64)
    src = SnapshotSource(data)
    uniq = int(src.uniq_lens.sum())
    total = int(src.manifest.total_bytes)
    assert uniq < total // 8  # the premise: heavy duplication
    resp = SnapshotResponder(src, chunk_budget=(uniq + total) // 2)
    resp.begin_payloads()
    replies = resp.handle(sn.decode_snapshot(sn.encode_want_all()))
    assert resp.failed is None, resp.failed
    assert resp.finished and resp.cold
    assert resp.chunk_bytes_sent == uniq  # bills unique, not positions
    assert len(replies) == 1  # the LogSlice
    # ... and a budget below the unique bytes still fails structured
    resp2 = SnapshotResponder(src, chunk_budget=uniq - 1)
    resp2.begin_payloads()
    [fail] = resp2.handle(sn.decode_snapshot(sn.encode_want_all()))
    assert sn.decode_snapshot(fail).kind == sn.SN_FAIL
    assert isinstance(resp2.failed, ProtocolError)


def test_cold_pump_is_paced_by_encoder_high_water():
    """_send_replies must NOT queue a whole cold dataset at once: the
    LogSlice pump parks at the encoder's high-water mark and resumes on
    drain, so responder memory stays ~high_water while the wire bytes
    still arrive complete and in order (on_done strictly last)."""
    from dat_replication_protocol_tpu.runtime.snapshot_driver import (
        LogSlice,
        _send_replies,
    )
    from dat_replication_protocol_tpu.session.encoder import Encoder
    from dat_replication_protocol_tpu.wire.framing import CAP_SNAPSHOT

    data = _dataset(1 << 20, seed=13)
    src = SnapshotSource(data)
    log = src.cold_log()
    hw = 64 * 1024
    enc = Encoder(high_water=hw, peer_caps=CAP_SNAPSHOT)
    done = []
    _send_replies(enc, [LogSlice(log, log.start, log.end)], 16 * 1024,
                  on_done=lambda: done.append(enc.buffered_bytes))
    total = log.end - log.start
    # the queue parked at the mark instead of swallowing the dataset
    assert enc.buffered_bytes < total // 2
    assert enc.buffered_bytes <= hw + 16 * 1024
    assert not done  # a parked pump has not finished
    got = bytearray()
    peak = enc.buffered_bytes
    while len(got) < total:
        chunk = enc.read(8 * 1024)
        assert chunk, (len(got), total)
        got += chunk
        peak = max(peak, enc.buffered_bytes)
    assert bytes(got) == log.read_from(log.start)  # complete, in order
    assert peak <= hw + 16 * 1024  # paced throughout, not just at start
    assert done  # ... and on_done fired exactly once, after the last push
    assert len(done) == 1
