"""TYPE_SNAPSHOT wire layer (ISSUE 12): payload codec round-trips,
structural-corruption rejection, and the session-layer capability
contract — an un-negotiated encoder cannot emit snapshot frames at all
(the golden byte-exact doctrine ChangeBatch and Reconcile established),
and a corrupt snapshot payload destroys the session with ONE structured
ProtocolError."""

from __future__ import annotations

import numpy as np
import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.wire import snapshot_codec as sn
from dat_replication_protocol_tpu.wire.framing import (
    CAP_SNAPSHOT,
    TYPE_SNAPSHOT,
    ProtocolError,
    frame,
)

_MAN = sn.SnapshotManifest(
    n_positions=5, n_chunks=4, total_bytes=12345,
    root=bytes(range(32)), wire_offset=777,
    avg_bits=13, min_size=2048, max_size=32768)


# -- payload codec -----------------------------------------------------------


def test_codec_roundtrips():
    cells = np.arange(36, dtype=np.uint32).reshape(3, 12)
    digs = np.arange(64, dtype=np.uint8).reshape(2, 32)
    chunks = [(bytes(range(32)), b"hello"), (bytes(32), b"")]
    ranks = np.array([3, 0, 2, 1, 3], dtype=np.int64)
    for payload, checks in [
        (sn.encode_begin(_MAN), dict(kind=sn.SN_BEGIN)),
        (sn.encode_symbols(7, cells), dict(kind=sn.SN_SYMBOLS, start=7)),
        (sn.encode_want_more(9), dict(kind=sn.SN_WANT, mode=sn.WANT_MORE,
                                      n=9)),
        (sn.encode_want_digests(digs), dict(kind=sn.SN_WANT,
                                            mode=sn.WANT_DIGESTS, n=2)),
        (sn.encode_want_all(), dict(kind=sn.SN_WANT, mode=sn.WANT_ALL)),
        (sn.encode_chunks(chunks), dict(kind=sn.SN_CHUNKS, n=2)),
        (sn.encode_done(11, ranks), dict(kind=sn.SN_DONE, n=11)),
        (sn.encode_fail(3, "why"), dict(kind=sn.SN_FAIL, n=3,
                                        reason="why")),
    ]:
        msg = sn.decode_snapshot(payload)
        for k, v in checks.items():
            assert getattr(msg, k) == v, (k, payload)
    man = sn.decode_snapshot(sn.encode_begin(_MAN)).manifest
    assert man == _MAN
    msg = sn.decode_snapshot(sn.encode_symbols(7, cells))
    assert np.array_equal(msg.cells, cells)
    msg = sn.decode_snapshot(sn.encode_want_digests(digs))
    assert np.array_equal(msg.digests, digs)
    msg = sn.decode_snapshot(sn.encode_chunks(chunks))
    assert [(bytes(d), bytes(c)) for d, c in msg.chunks] == chunks
    msg = sn.decode_snapshot(sn.encode_done(11, ranks))
    assert np.array_equal(msg.ranks, ranks)


@pytest.mark.parametrize("payload", [
    b"",                                            # empty
    bytes([9]),                                     # unknown subtype
    bytes([sn.SN_BEGIN, 99]),                       # bad version
    sn.encode_begin(_MAN)[:-1],                     # torn params
    sn.encode_begin(_MAN) + b"x",                   # trailing bytes
    sn.encode_symbols(0, np.zeros((2, 12), np.uint32))[:-3],  # torn cells
    bytes([sn.SN_WANT]),                            # no mode
    bytes([sn.SN_WANT, 7]),                         # unknown mode
    sn.encode_want_all() + b"\x00",                 # trailing bytes
    sn.encode_want_digests(np.zeros((2, 32), np.uint8))[:-1],  # torn digest
    sn.encode_chunks([(bytes(32), b"abc")])[:-1],   # torn chunk body
    sn.encode_chunks([(bytes(32), b"abc")]) + b"z",  # trailing bytes
    sn.encode_done(1, np.array([0, 1]))[:-1],       # torn rank varint
    sn.encode_done(1, np.array([0, 1])) + b"q",     # trailing bytes
    # byzantine DONE: a 2^40-position claim in a tiny payload must fail
    # structured BEFORE any allocation, not MemoryError/OOM
    bytes([sn.SN_DONE]) + b"\x00" + b"\x80\x80\x80\x80\x80\x80\x80\x80\x80\x02",
])
def test_codec_rejects_structural_corruption(payload):
    with pytest.raises(ValueError):
        sn.decode_snapshot(payload)


def test_encode_done_tail_matches_encode_done():
    # the cacheable ranks blob (SnapshotSource.done_payload) must stay
    # byte-identical to the direct encode — one layout, two call shapes
    ranks = np.array([0, 5, 2, 700, 1], np.int64)
    tail = sn.encode_done_tail(ranks)
    assert sn.encode_done(7, ranks) == \
        bytes((sn.SN_DONE,)) + b"\x07" + tail
    assert sn.encode_done(7, tail=tail) == sn.encode_done(7, ranks)
    with pytest.raises(ValueError, match="1-D"):
        sn.encode_done_tail(np.array([[1]], np.int64))


def test_iter_frames_walks_a_recorded_stream():
    # the shared frame walker (framing.iter_frames) is the one owner of
    # the header walk: every (start, type, payload, end) must tile the
    # wire exactly, large-payload (multi-byte varint) frames included
    from dat_replication_protocol_tpu.wire.framing import iter_frames
    payloads = [sn.encode_want_all(), b"\x05" + b"x" * 300,
                sn.encode_want_more(9)]
    wire = b"".join(frame(TYPE_SNAPSHOT, p) for p in payloads)
    seen = list(iter_frames(wire))
    assert [wire[p0:end] for _s, _t, p0, end in seen] == payloads
    assert all(t == TYPE_SNAPSHOT for _s, t, _p0, _e in seen)
    assert seen[0][0] == 0 and seen[-1][3] == len(wire)
    assert [s for s, _t, _p0, _e in seen[1:]] == \
        [e for _s, _t, _p0, e in seen[:-1]]  # frames tile, no gaps


def test_begin_rejects_more_unique_chunks_than_positions():
    bad = sn.SnapshotManifest(
        n_positions=2, n_chunks=3, total_bytes=10, root=bytes(32),
        wire_offset=0, avg_bits=13, min_size=1, max_size=10)
    with pytest.raises(ValueError, match="unique chunks"):
        sn.decode_snapshot(sn.encode_begin(bad))


def test_begin_golden_bytes_are_stable():
    # the manifest layout is wire contract (WIRE.md "Snapshot"): any
    # byte-level change is a protocol fork and must be deliberate
    assert sn.encode_begin(_MAN).hex() == (
        "0001" + "05" + "04" + "b960"
        + bytes(range(32)).hex()
        + "8906" + "0d" + "8010" + "808002")


# -- session-layer integration ----------------------------------------------


def test_unnegotiated_encoder_refuses_snapshot_frames_and_stays_golden():
    e = protocol.encode()
    with pytest.raises(ValueError, match="CAP_SNAPSHOT"):
        e.snapshot_frame(sn.encode_want_all())
    e.change({"key": "a", "change": 1, "from": 0, "to": 1})
    e.finalize()
    wire = e.read()
    ref = protocol.encode()
    ref.change({"key": "a", "change": 1, "from": 0, "to": 1})
    ref.finalize()
    assert wire == ref.read()  # byte-exact: the refusal left no residue


def test_decoder_advertises_cap_snapshot():
    assert protocol.Decoder.capabilities() & CAP_SNAPSHOT


def test_snapshot_frames_count_in_frame_accounting():
    e = protocol.encode(peer_caps=CAP_SNAPSHOT)
    d = protocol.decode()
    seen = []
    d.snapshot(lambda m, done: (seen.append(m), done()))
    e.change({"key": "x", "change": 1, "from": 0, "to": 1})
    e.snapshot_frame(sn.encode_want_more(1))
    e.change({"key": "y", "change": 2, "from": 0, "to": 1})
    e.finalize()
    wire = e.read()
    for off in range(0, len(wire), 5):
        d.write(wire[off:off + 5])
    d.end()
    assert d.finished and len(seen) == 1
    assert seen[0].kind == sn.SN_WANT and seen[0].mode == sn.WANT_MORE
    assert d.snapshot_frames == 1
    assert d._frames_delivered() == 3
    ckpt = d.checkpoint()
    assert ckpt.frame == 3 and ckpt.wire_offset == len(wire)


def test_unhandled_snapshot_frames_drop_without_deadlock():
    e = protocol.encode(peer_caps=CAP_SNAPSHOT)
    d = protocol.decode()  # no snapshot handler registered
    e.snapshot_frame(sn.encode_want_all())
    e.change({"key": "x", "change": 1, "from": 0, "to": 1})
    e.finalize()
    d.write(e.read())
    d.end()
    assert d.finished and d.changes == 1 and d.snapshot_frames == 1


def test_corrupt_snapshot_payload_is_structured_protocol_error():
    d = protocol.decode()
    errs = []
    d.on_error(errs.append)
    d.write(frame(TYPE_SNAPSHOT, bytes([250, 1])))
    assert d.destroyed
    assert isinstance(errs[0], ProtocolError)
    assert errs[0].offset is not None and errs[0].frame == 0


def test_snapshot_frame_refused_with_open_blob():
    e = protocol.encode(peer_caps=CAP_SNAPSHOT)
    b = e.blob(4)
    b.write(b"ab")
    with pytest.raises(ValueError, match="blob open"):
        e.snapshot_frame(sn.encode_want_all())
    b.end(b"cd")
    e.finalize()
