"""Chip-mutex tests (round-4 verdict weak #1: a concurrent diagnostic
contaminated the round's only driver-shaped capture; the flock is the
fix and must actually exclude across processes)."""

import json
import os
import subprocess
import sys
import time

from dat_replication_protocol_tpu.utils import chiplock


def test_uncontended_acquire(tmp_path, monkeypatch):
    monkeypatch.setenv("DAT_CHIP_LOCK", str(tmp_path / "chip.lock"))
    with chiplock.chip_lock(max_wait=1.0) as lease:
        assert lease.held and lease.uncontended
        assert lease.as_fields()["uncontended"] is True
        assert lease.as_fields()["chip_lock"]["held"] is True


def test_reentrant_same_path_excludes_across_processes(tmp_path, monkeypatch):
    lock = str(tmp_path / "chip.lock")
    monkeypatch.setenv("DAT_CHIP_LOCK", lock)
    # a child process holds the lock for ~1.2 s; the parent must observe
    # contention, then win once the child exits
    child = subprocess.Popen(
        [sys.executable, "-c", (
            "import os, sys, time;"
            "sys.path.insert(0, os.getcwd());"
            "os.environ['DAT_CHIP_LOCK'] = sys.argv[1];"
            "from dat_replication_protocol_tpu.utils.chiplock import chip_lock\n"
            "with chip_lock(max_wait=0.1) as l:\n"
            "    assert l.held\n"
            "    print('HELD', flush=True)\n"
            "    time.sleep(1.2)\n"
        ), lock],
        stdout=subprocess.PIPE, text=True, cwd=os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))),
    )
    assert child.stdout.readline().strip() == "HELD"
    t0 = time.monotonic()
    with chiplock.chip_lock(max_wait=10.0, poll_s=0.1) as lease:
        waited = time.monotonic() - t0
        assert lease.held
        assert not lease.uncontended  # had to wait for the child
        assert lease.waited_s > 0
        assert 0.5 < waited < 8.0
    child.wait(timeout=5)


def test_timeout_runs_lockless_but_says_so(tmp_path, monkeypatch):
    lock = str(tmp_path / "chip.lock")
    monkeypatch.setenv("DAT_CHIP_LOCK", lock)
    child = subprocess.Popen(
        [sys.executable, "-c", (
            "import os, sys, time;"
            "sys.path.insert(0, os.getcwd());"
            "os.environ['DAT_CHIP_LOCK'] = sys.argv[1];"
            "from dat_replication_protocol_tpu.utils.chiplock import chip_lock\n"
            "with chip_lock() as l:\n"
            "    print('HELD', flush=True)\n"
            "    time.sleep(3.0)\n"
        ), lock],
        stdout=subprocess.PIPE, text=True, cwd=os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))),
    )
    assert child.stdout.readline().strip() == "HELD"
    with chiplock.chip_lock(max_wait=0.3, poll_s=0.05) as lease:
        # peer never releases within the budget: run anyway, record it
        assert not lease.held
        fields = lease.as_fields()
        assert fields["uncontended"] is False
        assert fields["chip_lock"]["held"] is False
    child.kill()
    child.wait(timeout=5)


def test_crashed_holder_releases_lock(tmp_path, monkeypatch):
    """flock dies with the process: a crashed diagnostic can never wedge
    the chip lock (the reason flock was chosen over pid files)."""
    lock = str(tmp_path / "chip.lock")
    monkeypatch.setenv("DAT_CHIP_LOCK", lock)
    child = subprocess.Popen(
        [sys.executable, "-c", (
            "import os, sys;"
            "sys.path.insert(0, os.getcwd());"
            "os.environ['DAT_CHIP_LOCK'] = sys.argv[1];"
            "from dat_replication_protocol_tpu.utils.chiplock import chip_lock\n"
            "ctx = chip_lock()\n"
            "ctx.__enter__()\n"
            "print('HELD', flush=True)\n"
            "os._exit(9)\n"  # simulated crash: no __exit__, no unlock
        ), lock],
        stdout=subprocess.PIPE, text=True, cwd=os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))),
    )
    assert child.stdout.readline().strip() == "HELD"
    child.wait(timeout=5)
    with chiplock.chip_lock(max_wait=2.0, poll_s=0.05) as lease:
        assert lease.held  # kernel released the dead holder's flock


def test_lease_fields_json_serializable(tmp_path, monkeypatch):
    monkeypatch.setenv("DAT_CHIP_LOCK", str(tmp_path / "chip.lock"))
    with chiplock.chip_lock(max_wait=0.5) as lease:
        json.dumps(lease.as_fields())
