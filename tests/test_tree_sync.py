"""Interactive Merkle descent: correctness + O(diff log n) transfer."""

import random

import numpy as np
import pytest

from dat_replication_protocol_tpu.ops import merkle
from dat_replication_protocol_tpu.runtime.tree_sync import (
    TreeSyncSession,
    sync,
)


def _session(leaves):
    hh, hl = merkle.pad_leaves(*merkle.digests_to_device(leaves))
    return TreeSyncSession(*merkle.build_tree(hh, hl))


def _leaves(n, seed=0):
    rng = random.Random(seed)
    return [rng.randbytes(32) for _ in range(n)]


def test_equal_trees_one_message():
    a = _leaves(256)
    transcript = []
    assert sync(_session(a), _session(a), transcript) == []
    assert transcript == [("a->b", 32), ("b->a", 1)]  # root handshake


def test_finds_exact_diff_and_meters_transfer():
    n = 1024
    a = _leaves(n, seed=2)
    b = list(a)
    changed = sorted(random.Random(3).sample(range(n), 5))
    for i in changed:
        b[i] = bytes(32)
    transcript = []
    got = sync(_session(a), _session(b), transcript)
    assert got == changed
    assert got == merkle.host_diff(a, b)
    total = sum(nb for _, nb in transcript)
    # O(diff * log n * 64B) beats shipping all n digests by far
    assert total < n * 32 // 4, f"descent moved {total} bytes"
    # log n rounds: request+response per level below the root
    n_msgs = len(transcript)
    assert n_msgs == 2 + 2 * 10  # root handshake + 10 levels of (req, reply)


def test_single_change_transfer_is_logarithmic():
    n = 4096
    a = _leaves(n, seed=5)
    b = list(a)
    b[1234] = bytes(32)
    transcript = []
    assert sync(_session(a), _session(b), transcript) == [1234]
    total = sum(nb for _, nb in transcript)
    # frontier never exceeds 1 node: 64B request + 1B reply per level
    assert total <= 33 + 12 * (64 + 1), total


def test_mismatched_widths_rejected():
    with pytest.raises(ValueError, match="equal"):
        sync(_session(_leaves(8)), _session(_leaves(16)))
