"""Cluster-sim chaos sweep (ISSUE 15 acceptance): 20 seeds tier-1 +
100-seed slow soak.  Every seed derives a full scenario — N in
{4, 16, 64}, a partition that heals at a seeded round, chaos links
(drops / stalls / flips / re-segmentation), plus one of churn /
flash-crowd / byzantine — and asserts the convergence contract:

* every partition heals to BYTE-IDENTICAL healthy replica content
  digests within the bounded round budget (``rounds_bound``);
* with no byzantine replica, the converged digest equals the
  ground-truth union exactly;
* no cross-partition exchange succeeds during the cut (the injector
  is the oracle: ``partition_scenario`` is shared by the plan
  generator and this test);
* the byzantine replica is quarantined with a structured divergence
  while the healthy set converges — and every quarantine event is
  EXPLAINABLE: the quarantined peer is the byzantine replica, or the
  pair's link drew the ``flip`` scenario (wire corruption is the only
  other corruption source; nothing is ever quarantined silently or
  spuriously).
"""

import pytest

from dat_replication_protocol_tpu.cluster import ClusterSim
from dat_replication_protocol_tpu.session.faults import FaultPlan

BYZ_ARMS = ("wrong-symbol", "wrong-chunk", "feed-corrupt")


def _scenario(seed: int) -> dict:
    """The seed's full scenario — deterministic, shared with the soak."""
    n = (4, 16, 64)[seed % 3]
    kw: dict = {"n": n, "seed": seed, "chaos": True}
    if n == 64:
        # smaller per-replica sets keep the 64-replica seeds inside the
        # tier-1 runtime budget; the *shape* (partition/churn/chaos) is
        # what the sweep certifies, and wire cost scales with diff
        kw.update(records_per=12, divergence=3)
    arm = None
    mode = seed % 4
    if mode == 1:
        kw.update(churn=True, fanout=True, fanout_retention=2048)
    elif mode == 2 and n <= 16:
        kw.update(flash_crowd=2)
    elif mode == 3:
        arm = BYZ_ARMS[(seed // 4) % len(BYZ_ARMS)]
        kw.update(byzantine=1 if n == 4 else 2, byzantine_arm=arm)
        if arm == "feed-corrupt":
            kw.update(fanout=True)
    kw["_arm"] = arm
    return kw


def _run_seed(seed: int) -> None:
    kw = _scenario(seed)
    arm = kw.pop("_arm")
    n = kw.pop("n")
    sim = ClusterSim(n, **kw)
    out = sim.run()
    # 1. convergence within the bounded round budget
    assert out["converged"], (
        f"seed {seed} (n={n}) did not converge within {out['bound']} "
        f"rounds: digests {out['digests']}")
    assert out["rounds"] <= out["bound"]
    # 2. byte-identical healthy replicas; exact union with no byzantine
    healthy = {sim.nodes[k].content_digest().hex()
               for k in sim.healthy()}
    assert len(healthy) == 1, f"seed {seed}: healthy replicas diverge"
    if sim.byzantine_key is None:
        assert healthy == {out["expected_digest"]}, (
            f"seed {seed}: converged to the wrong content")
    # 3. partition oracle: the cut really cut — no successful
    # cross-group exchange during [cut_round, heal_round)
    sc = out["partition"]
    minority = sc["groups"][0]
    for ev in sim.events:
        if not sc["cut_round"] <= ev["round"] < sc["heal_round"]:
            continue
        for ex in ev["exchanges"]:
            if ex["outcome"] != "ok":
                continue
            li = sim._index.get(ex["initiator"])
            lt = sim._index.get(ex["responder"])
            if li is None or lt is None or li >= sim.n0 or lt >= sim.n0:
                continue  # flash joiners sit outside the cut schedule
            assert (li in minority) == (lt in minority), (
                f"seed {seed}: exchange {ex} crossed the partition "
                f"during the cut")
    # 4. byzantine: quarantined with a structured divergence, and every
    # quarantine explainable against injector ground truth.  The
    # wrong-chunk arm lies only while a diff makes honest peers request
    # its content — once the mesh converges around it there is nothing
    # left to lie about, so quarantine is guaranteed only for the arms
    # that corrupt unconditionally; for wrong-chunk the guarantee is
    # that every lie was REFUSED with a structured divergence naming
    # the liar (the targeted unit arm proves its quarantine path).
    if sim.byzantine_key is not None:
        if arm in ("wrong-symbol", "feed-corrupt"):
            assert any(q["peer"] == sim.byzantine_key
                       for q in out["quarantines"]), (
                f"seed {seed}: byzantine ({arm}) never quarantined")
        byz_corrupt = [
            ex for ev in sim.events for ex in ev["exchanges"]
            if ex["outcome"] == "corruption"
            and sim.byzantine_key in (ex["initiator"], ex["responder"])]
        if arm == "wrong-chunk":
            assert byz_corrupt, (
                f"seed {seed}: wrong-chunk byzantine never caught lying")
            assert any(
                f"repair records from '{sim.byzantine_key}'"
                in ex["error"] for ex in byz_corrupt), (
                f"seed {seed}: no wrong-chunk lie surfaced a "
                f"divergence naming the liar")
    for q in out["quarantines"]:
        if sim.byzantine_key in (q["by"], q["peer"]):
            continue
        li, lt = sim._index[q["by"]], sim._index[q["peer"]]
        scen, _rnd = FaultPlan.link_scenario(seed, sim.n0,
                                             (min(li, lt), max(li, lt)))
        assert scen == "flip", (
            f"seed {seed}: quarantine {q} has no corruption source — "
            f"link scenario is {scen!r}")
    # 5. anti-entropy did real work over real wire
    assert out["wire_bytes"] > 0


@pytest.mark.parametrize("seed", range(20))
def test_cluster_chaos_sweep(seed):
    _run_seed(seed)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(20, 120))
def test_cluster_chaos_soak(seed):
    _run_seed(seed)
