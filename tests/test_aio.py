"""Session over asyncio streams: event-loop pumps, deferred acks.

The asyncio analogue of test_transport.py's socket suite (reference
semantics: example.js:53 piping over any async stream).
"""

import asyncio

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.session.aio import session_over_asyncio


def _run(coro):
    return asyncio.run(coro)


def test_changes_and_blob_over_asyncio():
    enc, dec = protocol.encode(), protocol.decode()
    got = []
    dec.change(lambda c, done: (got.append(("change", c.key)), done()))
    dec.blob(
        lambda b, done: b.collect(lambda d: (got.append(("blob", d)), done()))
    )
    dec.finalize(lambda done: (got.append(("finalize",)), done()))

    async def main():
        enc.change({"key": "a", "change": 1, "from": 0, "to": 1})
        ws = enc.blob(11)
        ws.write(b"hello ")
        ws.end(b"world")
        enc.change({"key": "b", "change": 2, "from": 1, "to": 2})
        enc.finalize()
        await asyncio.wait_for(session_over_asyncio(enc, dec), 30)

    _run(main())
    assert got == [
        ("change", "a"),
        ("blob", b"hello world"),
        ("change", "b"),
        ("finalize",),
    ]
    assert enc.bytes == dec.bytes and dec.changes == 2 and dec.blobs == 1


def test_deferred_ack_stalls_and_resumes():
    enc, dec = protocol.encode(), protocol.decode()
    order = []

    def on_change(c, done):
        order.append(f"change-{c.key}")
        # ack later from the event loop: the pump must stall (not drop or
        # reorder) until the deferred done fires
        asyncio.get_running_loop().call_later(0.05, done)

    dec.change(on_change)
    dec.finalize(lambda done: (order.append("finalize"), done()))

    async def main():
        for i in range(5):
            enc.change({"key": str(i), "change": i, "from": i, "to": i + 1})
        enc.finalize()
        await asyncio.wait_for(session_over_asyncio(enc, dec), 30)

    _run(main())
    assert order == [f"change-{i}" for i in range(5)] + ["finalize"]


def test_large_blob_backpressure_over_asyncio():
    enc, dec = protocol.encode(), protocol.decode()
    total = (1 << 20) + 12345
    seen = bytearray()

    def on_blob(b, done):
        b.on_data(lambda piece: seen.extend(piece))
        b.on_end(lambda: done())

    dec.blob(on_blob)

    async def feed():
        ws = enc.blob(total)
        sent = 0
        while sent < total:
            n = min(64 * 1024, total - sent)
            ws.write(bytes([sent % 251]) * n)
            sent += n
            await asyncio.sleep(0)  # yield so pumps interleave
        ws.end()
        enc.finalize()

    async def main():
        await asyncio.wait_for(
            asyncio.gather(feed(), session_over_asyncio(enc, dec)), 60
        )

    _run(main())
    assert len(seen) == total
    assert dec.blobs == 1


def test_decoder_destroy_mid_blob_does_not_hang():
    # regression: a destroyed decoder leaves the socket unread; the
    # session must abort the stuck sender instead of deadlocking in
    # writer.drain() (and teardown must not hang on a flushing close)
    enc, dec = protocol.encode(), protocol.decode()

    def on_blob(b, done):
        b.on_data(lambda piece: dec.destroy(RuntimeError("app bail")))

    dec.blob(on_blob)
    dec.on_error(lambda e: None)
    enc.on_error(lambda e: None)

    async def main():
        ws = enc.blob(4 << 20)
        ws.end(b"\xab" * (4 << 20))
        enc.finalize()
        await asyncio.wait_for(session_over_asyncio(enc, dec), 10)

    _run(main())
    assert dec.destroyed


def test_decoder_destroy_with_idle_sender_does_not_hang():
    # regression: receiver exits while the sender is parked in
    # readable.wait() on an idle, unfinalized encoder — the session must
    # destroy the encoder (waking the park) rather than deadlock
    enc, dec = protocol.encode(), protocol.decode()
    errs = []
    dec.change(lambda c, done: dec.destroy(RuntimeError("bail")))
    dec.on_error(lambda e: errs.append(e))
    enc.on_error(lambda e: errs.append(e))

    async def main():
        enc.change({"key": "x", "change": 1, "from": 0, "to": 1})
        # deliberately not finalized: the encoder goes idle
        await asyncio.wait_for(session_over_asyncio(enc, dec), 10)

    _run(main())
    assert dec.destroyed and enc.destroyed


def test_async_fault_injector_resegmentation_is_transparent():
    """AsyncFaultyReader (the chaos harness's asyncio face,
    session/faults.py) slicing the stream into 1..7-byte pieces must not
    change the decoded session — every header/payload straddle the
    event-loop pump can see, exercised in one pass."""
    from dat_replication_protocol_tpu.session.aio import (
        recv_over_async,
        send_over_async,
    )
    from dat_replication_protocol_tpu.session.faults import (
        AsyncFaultyReader,
        FaultPlan,
    )

    enc, dec = protocol.encode(), protocol.decode()
    got = []
    dec.change(lambda c, done: (got.append(("change", c.key)), done()))
    dec.blob(
        lambda b, done: b.collect(lambda d: (got.append(("blob", d)), done()))
    )

    async def main():
        import socket

        a, b = socket.socketpair()
        a.setblocking(False)
        b.setblocking(False)
        _, writer = await asyncio.open_connection(sock=a)
        reader, writer_b = await asyncio.open_connection(sock=b)
        enc.change({"key": "a", "change": 1, "from": 0, "to": 1})
        ws = enc.blob(11)
        ws.write(b"hello ")
        ws.end(b"world")
        enc.change({"key": "b", "change": 2, "from": 1, "to": 2})
        enc.finalize()
        chaotic = AsyncFaultyReader(
            reader, FaultPlan(seed=9, max_segment=7, latency_prob=0.1,
                              latency_s=0.001))
        await asyncio.wait_for(asyncio.gather(
            send_over_async(enc, writer),
            recv_over_async(dec, chaotic),
        ), 30)
        for w in (writer, writer_b):
            w.transport.abort()
            w.close()
        a.close()
        b.close()

    _run(main())
    assert got == [("change", "a"), ("blob", b"hello world"), ("change", "b")]
    assert dec.finished
