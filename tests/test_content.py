"""Content-addressing pipeline: chunk -> hash -> root, version deltas.

The composed dat workflow (chunked dedup exchange) over the device
pipeline; the CDC shift-tolerance property is what keeps deltas O(edit).
"""

import hashlib

import numpy as np
import pytest

from dat_replication_protocol_tpu.runtime import (
    content_address,
    delta,
    reassemble,
)


def _data(n: int, seed: int = 0) -> bytes:
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8
    ).tobytes()


def test_summary_shape_and_digests():
    data = _data(1 << 18)
    s = content_address(data, avg_bits=10)
    assert s.length == len(data)
    assert s.cuts[-1] == len(data)
    assert sorted(s.cuts) == s.cuts
    assert s.digests.shape == (len(s.cuts), 32)
    offs, lens = s.extents()
    assert int(lens.sum()) == len(data)
    for i in (0, len(s.cuts) // 2, len(s.cuts) - 1):
        piece = data[int(offs[i]):int(offs[i]) + int(lens[i])]
        assert s.digests[i].tobytes() == hashlib.blake2b(
            piece, digest_size=32
        ).digest()


def test_equal_content_equal_root_empty_delta():
    data = _data(1 << 17, seed=3)
    a = content_address(data, avg_bits=10)
    b = content_address(data, avg_bits=10)
    assert a.root == b.root
    assert delta(a, b) == []


def test_delta_is_o_edit_and_reassembles():
    data = _data(1 << 18, seed=5)
    # insertion near the front: positional schemes would shift every
    # later chunk; content-defined cuts must keep the delta local
    edited = data[:1000] + b"INSERTED-BYTES" * 8 + data[1000:]
    old = content_address(data, avg_bits=10)
    new = content_address(edited, avg_bits=10)
    assert old.root != new.root
    d = delta(old, new)
    assert 1 <= len(d) <= 4, f"delta {len(d)} chunks of {new.nchunks}"
    offs, lens = new.extents()
    sent = {
        i: edited[int(offs[i]):int(offs[i]) + int(lens[i])] for i in d
    }
    assert reassemble(new, data, old, sent) == edited


def test_reassemble_rejects_corrupt_chunk():
    data = _data(1 << 16, seed=7)
    edited = data + b"tail-change"
    old = content_address(data, avg_bits=10)
    new = content_address(edited, avg_bits=10)
    d = delta(old, new)
    offs, lens = new.extents()
    sent = {i: edited[int(offs[i]):int(offs[i]) + int(lens[i])] for i in d}
    k = d[0]
    sent[k] = b"X" + sent[k][1:]
    with pytest.raises(ValueError, match="digest mismatch"):
        reassemble(new, data, old, sent)


def test_empty_input():
    s = content_address(b"")
    assert s.nchunks == 0 and s.length == 0 and s.root == b"\0" * 32
    t = content_address(b"")
    assert delta(s, t) == []
