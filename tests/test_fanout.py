"""Unit layer for the broadcast fan-out (ISSUE 9): the multi-reader
log's retention/cursor contract, the zero-copy scatter-gather read
path, the fan-out server's windowed dispatch, the three-stage overload
contract (admission -> window stall -> shed), and the hash-once
telemetry proof.  The chaos sweep lives in test_fanout_faults.py.
"""

from __future__ import annotations

import socket
import threading
import time

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.fanout import (
    BroadcastLog,
    FanoutBusy,
    FanoutServer,
    PeerShed,
    SnapshotNeeded,
)
from dat_replication_protocol_tpu.session.resume import ResumeError

WIRE = bytes(range(256)) * 300  # 76,800 bytes, content position-coded


def _counting_sink(buf: bytearray):
    def sink(views):
        n = 0
        for v in views:
            buf.extend(bytes(v))
            n += len(v)
        return n
    return sink


# -- BroadcastLog -------------------------------------------------------------


def test_log_append_read_slices_roundtrip_across_segment_kinds():
    """Small appends coalesce, large ones become their own segments;
    reads stitch both byte-exactly at arbitrary offsets."""
    log = BroadcastLog(retention_budget=1 << 20)
    log.append(b"a" * 100)        # coalesced tail
    log.append(b"b" * 8192)       # own segment (freezes the tail)
    log.append(b"c" * 50)         # new tail
    log.append(b"d" * 5000)       # own segment
    whole = b"a" * 100 + b"b" * 8192 + b"c" * 50 + b"d" * 5000
    assert log.end == len(whole)
    assert log.read_from(0) == whole
    for off in (0, 1, 99, 100, 101, 8291, 8292, 8343, 13341, len(whole)):
        assert log.read_from(off) == whole[off:]


def test_log_read_slices_are_zero_copy_views():
    """The scatter-gather contract: read_slices returns memoryviews
    aliasing the log's own segments — no payload copy per read."""
    log = BroadcastLog()
    big = b"x" * 10000
    log.append(big)
    views = log.read_slices(0, 10000)
    assert all(isinstance(v, memoryview) for v in views)
    # the view aliases the very bytes object append stored (append of a
    # bytes-sized chunk re-wraps but must not copy per reader: two
    # reads alias the SAME underlying object)
    v1 = log.read_slices(0, 10000)[0]
    v2 = log.read_slices(0, 10000)[0]
    assert v1.obj is v2.obj
    v1.release()
    v2.release()
    for v in views:
        v.release()


def test_log_read_slices_respects_max_iov_and_max_bytes():
    log = BroadcastLog()
    for _ in range(10):
        log.append(b"s" * 5000)  # 10 segments
    views = log.read_slices(0, 1 << 20, max_iov=4)
    assert len(views) == 4
    assert sum(len(v) for v in views) == 20000
    views = log.read_slices(2500, 6000)
    assert sum(len(v) for v in views) == 6000


def test_log_retains_full_budget_window_for_late_joiners():
    """Below the retention budget the log does NOT trim behind fast
    readers: a late joiner attaches at any retained offset."""
    log = BroadcastLog(retention_budget=1 << 20)
    c1 = log.attach("fast", 0)
    log.append(b"k" * 10000)
    log.ack(c1, 10000)
    assert log.start == 0  # history retained for late joiners
    late = log.attach("late", 5000)
    assert log.read_from(5000) == b"k" * 5000
    log.detach(late)
    log.detach(c1)


def test_log_budget_trim_invalidates_laggard_with_structured_error():
    """Over budget, the budget wins: the laggard's cursor is
    invalidated and every path out of it is a structured SnapshotNeeded
    naming the retained range — never a silent short read."""
    log = BroadcastLog(retention_budget=1000)
    lag = log.attach("lag", 0)
    ok = log.attach("ok", 0)
    log.append(b"y" * 4000)
    log.ack(ok, 4000)  # triggers the budget trim
    assert log.start == 3000
    assert lag.invalidated
    with pytest.raises(SnapshotNeeded) as ei:
        log.read_slices(0, 100)
    assert ei.value.retained == (3000, 4000)
    assert "[3000, 4000)" in str(ei.value)
    with pytest.raises(SnapshotNeeded):
        log.ack(lag, 500)
    with pytest.raises(SnapshotNeeded) as ei:
        log.attach("late", 0)
    assert ei.value.retained == (3000, 4000)
    # attach beyond production is the OTHER structured error
    with pytest.raises(ResumeError):
        log.attach("ahead", 4001)


def test_log_enforce_retention_without_acks():
    """Budget pressure from a burst of appends is enforced by the
    dispatcher hook, not the O(1) write path."""
    log = BroadcastLog(retention_budget=512)
    log.append(b"z" * 2048)
    assert log.start == 0  # append itself never trims (O(1) in peers)
    log.enforce_retention()
    assert log.start == 2048 - 512


def test_log_seal_refuses_append_and_seek_contract():
    log = BroadcastLog()
    log.append(b"q")
    log.seal()
    assert log.sealed
    with pytest.raises(ValueError):
        log.append(b"more")
    log2 = BroadcastLog()
    log2.seek(777)  # encoder journal-tee alignment
    assert (log2.start, log2.end) == (777, 777)
    log2.append(b"ab")
    assert log2.read_from(777) == b"ab"
    with pytest.raises(ValueError):
        log2.seek(0)  # non-empty


def test_encoder_attach_journal_into_broadcast_log_is_byte_exact():
    """The wiring the sidecar uses conceptually: an encoder tees its
    wire into the broadcast log; a decoder replaying from offset 0
    reproduces the session byte-exactly."""
    e = protocol.encode()
    log = BroadcastLog()
    e.attach_journal(log)
    e.change({"key": "a", "change": 1, "from": 0, "to": 1, "value": b"v"})
    ws = e.blob(5)
    ws.write(b"12")
    ws.end(b"345")
    e.finalize()
    parts = []
    while True:
        d = e.read(7)
        if d is None:
            break
        parts.append(d)
    assert log.read_from(0) == b"".join(parts)
    dec = protocol.decode()
    seen = []
    dec.change(lambda ch, done: (seen.append(ch.key), done()))
    dec.blob(lambda b, done: b.collect(lambda data: (seen.append(data),
                                                     done())))
    dec.write(log.read_from(0))
    dec.end()
    assert dec.finished and seen == ["a", b"12345"]


# -- FanoutServer -------------------------------------------------------------


def test_server_admission_is_stage_one_of_the_overload_contract():
    srv = FanoutServer(max_peers=2, stall_timeout=5.0)
    try:
        srv.attach_peer("a", sink=lambda vs: 0)
        srv.attach_peer("b", sink=lambda vs: 0)
        with pytest.raises(FanoutBusy) as ei:
            srv.attach_peer("c", sink=lambda vs: 0)
        assert ei.value.peers == 2 and ei.value.max_peers == 2
        with pytest.raises(ValueError):
            srv.attach_peer("a", sink=lambda vs: 0)  # duplicate key
        with pytest.raises(ValueError):
            srv.attach_peer("bad{key}", sink=lambda vs: 0)
        with pytest.raises(ValueError):
            srv.attach_peer(None, sink=lambda vs: 0)  # keys ride labels
        with pytest.raises(ValueError):
            srv.attach_peer("x", sink=lambda vs: 0, fd=1)  # both transports
    finally:
        srv.close()


def test_server_delivers_byte_exact_to_sink_and_fd_peers():
    srv = FanoutServer(stall_timeout=10.0)
    try:
        got = bytearray()
        p_sink = srv.attach_peer("sink", sink=_counting_sink(got))
        a, b = socket.socketpair()
        recv = bytearray()

        def reader():
            while True:
                d = b.recv(65536)
                if not d:
                    return
                recv.extend(d)

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        p_fd = srv.attach_peer("fd", fd=a.fileno())
        for off in range(0, len(WIRE), 4321):
            srv.publish(WIRE[off:off + 4321])
        srv.seal()
        assert srv.drain(15)
        assert p_sink.wait_done(5) and p_fd.wait_done(5)
        a.close()
        t.join(5)
        assert bytes(got) == WIRE
        assert bytes(recv) == WIRE
        st = p_sink.stats()
        assert st["sent_bytes"] == len(WIRE) and st["done"]
    finally:
        srv.close()


def test_server_late_joiner_attaches_mid_stream_at_retained_offset():
    srv = FanoutServer(stall_timeout=10.0)
    try:
        srv.publish(WIRE[:30000])
        tail = bytearray()
        p = srv.attach_peer("late", sink=_counting_sink(tail),
                            offset=30000)
        srv.publish(WIRE[30000:])
        srv.seal()
        assert srv.drain(10) and p.wait_done(5)
        assert bytes(tail) == WIRE[30000:]
    finally:
        srv.close()


def test_server_window_stall_bounds_only_the_slow_peer():
    """Stage two: a peer whose sink would-blocks accumulates backlog
    bounded by its own window; a healthy co-resident peer finishes at
    full speed meanwhile."""
    srv = FanoutServer(stall_timeout=30.0)
    try:
        fast = bytearray()
        slow_gate = threading.Event()
        slow = bytearray()

        def slow_sink(views):
            if not slow_gate.is_set():
                return 0  # would-block
            n = 0
            for v in views:
                slow.extend(bytes(v))
                n += len(v)
            return n

        p_fast = srv.attach_peer("fast", sink=_counting_sink(fast))
        p_slow = srv.attach_peer("slow", sink=slow_sink)
        t0 = time.monotonic()
        for off in range(0, len(WIRE), 8192):
            srv.publish(WIRE[off:off + 8192])
        srv.seal()
        assert p_fast.wait_done(10)
        fast_done = time.monotonic() - t0
        assert bytes(fast) == WIRE
        assert not p_slow.stats()["done"]
        assert fast_done < 5.0  # never convoyed behind the slow peer
        slow_gate.set()
        assert p_slow.wait_done(10)
        assert bytes(slow) == WIRE
    finally:
        srv.close()


def test_server_sheds_stalled_peer_and_neighbors_never_notice():
    """Stage three: no delivery progress for stall_timeout -> shed with
    a structured PeerShed; the healthy peer's stream is untouched."""
    srv = FanoutServer(stall_timeout=0.25)
    try:
        healthy = bytearray()
        p_ok = srv.attach_peer("ok", sink=_counting_sink(healthy))
        p_stuck = srv.attach_peer("stuck", sink=lambda vs: 0)
        for off in range(0, len(WIRE), 8192):
            srv.publish(WIRE[off:off + 8192])
        srv.seal()
        assert p_ok.wait_done(10)
        deadline = time.monotonic() + 5
        while p_stuck.shed_reason is None and time.monotonic() < deadline:
            time.sleep(0.02)
        assert p_stuck.shed_reason == "stall"
        with pytest.raises(PeerShed) as ei:
            p_stuck.raise_if_shed()
        assert ei.value.key == "stuck" and ei.value.reason == "stall"
        assert bytes(healthy) == WIRE
    finally:
        srv.close()


def test_server_sheds_byzantine_acker_with_structured_error():
    srv = FanoutServer(stall_timeout=10.0)
    try:
        got = bytearray()
        p = srv.attach_peer("byz", sink=_counting_sink(got),
                            explicit_ack=True)
        srv.publish(b"n" * 2000)
        deadline = time.monotonic() + 5
        while p.sent < 2000 and time.monotonic() < deadline:
            time.sleep(0.01)
        with pytest.raises(PeerShed) as ei:
            p.ack(99999)  # acking bytes never sent
        assert ei.value.reason == "byzantine"
        assert p.shed_reason == "byzantine"
    finally:
        srv.close()


def test_server_sheds_disconnected_fd_peer():
    srv = FanoutServer(stall_timeout=10.0)
    try:
        a, b = socket.socketpair()
        p = srv.attach_peer("gone", fd=a.fileno())
        b.close()  # peer vanishes
        srv.publish(b"w" * 70000)
        srv.publish(b"w" * 70000)  # EPIPE surfaces on a later writev
        deadline = time.monotonic() + 5
        while p.shed_reason is None and time.monotonic() < deadline:
            time.sleep(0.02)
        assert p.shed_reason == "disconnect"
        a.close()
    finally:
        srv.close()


def test_server_sheds_budget_trimmed_laggard_as_retention():
    srv = FanoutServer(retention_budget=4096, stall_timeout=30.0)
    try:
        drained = bytearray()
        lag = srv.attach_peer("lag", sink=lambda vs: 0)
        ok = srv.attach_peer("ok", sink=_counting_sink(drained))
        for _ in range(8):
            srv.publish(b"r" * 2000)
        deadline = time.monotonic() + 5
        while lag.shed_reason is None and time.monotonic() < deadline:
            time.sleep(0.02)
        assert lag.shed_reason == "retention"
        srv.seal()
        assert ok.wait_done(10)
        assert len(drained) == 16000
    finally:
        srv.close()


def test_explicit_ack_window_closes_and_reopens():
    """WAN shape: with explicit acks, unacked in-flight bytes are
    bounded by the peer's window; acking reopens it."""
    srv = FanoutServer(stall_timeout=30.0)
    try:
        got = bytearray()
        p = srv.attach_peer("wan", sink=_counting_sink(got),
                            window_bytes=1024, explicit_ack=True)
        srv.publish(b"h" * 10000)
        deadline = time.monotonic() + 5
        while len(got) < 1024 and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.1)  # give the dispatcher a chance to overshoot
        assert len(got) == 1024  # window-bounded in flight
        p.ack(1024)
        deadline = time.monotonic() + 5
        while len(got) < 2048 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(got) == 2048
        p.ack(2048)
        srv.seal()
        while len(got) < 10000 and time.monotonic() < deadline + 10:
            p.ack(p.sent)
            time.sleep(0.01)
        assert bytes(got) == b"h" * 10000
    finally:
        srv.close()


def test_hash_once_telemetry_proof(obs_enabled):
    """The headline economics, measured: decoding (hashing) happens
    ONCE at the source while N peers receive the bytes — the appended
    bytes counter is wire-sized, the sent counter is N x wire-sized,
    and the decode/digest path ran once regardless of peer count."""
    e = protocol.encode()
    for j in range(50):
        e.change({"key": f"k{j}", "change": j, "from": j, "to": j + 1,
                  "value": b"v" * 32})
    e.finalize()
    parts = []
    while True:
        d = e.read(4096)
        if d is None:
            break
        parts.append(d)
    wire = b"".join(parts)

    n_peers = 4
    srv = FanoutServer(stall_timeout=10.0)
    try:
        bufs = [bytearray() for _ in range(n_peers)]
        peers = [srv.attach_peer(f"p{i}", sink=_counting_sink(bufs[i]))
                 for i in range(n_peers)]
        dec = protocol.decode(backend="tpu")
        digs = []
        dec.on_digest(lambda kind, seq, d: digs.append(d))
        for off in range(0, len(wire), 1024):
            chunk = wire[off:off + 1024]
            srv.publish(chunk)   # fan-out: bytes only
            dec.write(chunk)     # digest work: exactly once
        dec.end()
        srv.seal()
        assert srv.drain(10)
        assert dec.finished and len(digs) == 50
        for buf in bufs:
            assert bytes(buf) == wire
        reg = obs_enabled.REGISTRY
        assert reg.counter("fanout.append.bytes").value == len(wire)
        assert reg.counter("fanout.sent.bytes").value == \
            n_peers * len(wire)
        for p in peers:
            p.close()
    finally:
        srv.close()


def test_peer_latency_stats_populate():
    srv = FanoutServer(stall_timeout=10.0)
    try:
        got = bytearray()
        p = srv.attach_peer("lat", sink=_counting_sink(got))
        for off in range(0, len(WIRE), 8192):
            srv.publish(WIRE[off:off + 8192])
        srv.seal()
        assert p.wait_done(10)
        st = p.stats()
        assert st["lat_p50_ms"] is not None
        assert st["lat_p99_ms"] is not None
        assert st["lat_p99_ms"] >= st["lat_p50_ms"]
    finally:
        srv.close()


def test_retention_enforced_with_zero_peers_attached():
    """Review regression: the dispatcher (started at construction) is
    the retention enforcer — a source publishing before any subscriber
    attaches must not grow the log past the budget."""
    srv = FanoutServer(retention_budget=4096, stall_timeout=30.0)
    try:
        for _ in range(16):
            srv.publish(b"g" * 1024)
        deadline = time.monotonic() + 5
        while srv.log.retained_bytes > 4096 and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        assert srv.log.retained_bytes <= 4096, srv.log.retained_bytes
        assert srv.log.end == 16384  # production unaffected
    finally:
        srv.close()


def test_invalidated_laggard_honest_ack_sheds_as_retention():
    """Review regression: an explicit-ack peer the budget trimmed past
    is a laggard, not an attacker — its next honest ack sheds it with
    reason 'retention', never 'byzantine'."""
    srv = FanoutServer(retention_budget=2048, stall_timeout=30.0)
    try:
        got = bytearray()
        lag = srv.attach_peer("lag", sink=_counting_sink(got),
                              explicit_ack=True)
        for _ in range(8):
            srv.publish(b"w" * 1024)
        # delivery keeps up (window default 1 MiB) but acks never come:
        # the budget trims past the cursor
        deadline = time.monotonic() + 5
        while srv.log.start == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert srv.log.start > 0
        deadline = time.monotonic() + 5
        while lag.sent < 4096 and time.monotonic() < deadline:
            time.sleep(0.01)
        with pytest.raises(PeerShed) as ei:
            lag.ack(lag.sent)  # honest: bytes really delivered
        assert ei.value.reason == "retention"
        assert lag.shed_reason == "retention"
    finally:
        srv.close()


def test_attach_past_retention_carries_snapshot_hint():
    """ISSUE 12: a server configured with a snapshot bootstrap hint
    attaches it to the SnapshotNeeded an attach refusal raises — the
    joiner learns the redirect IN the refusal.  Without a hint the
    field is None (the pre-bootstrap deployment, unchanged)."""
    from dat_replication_protocol_tpu.fanout import FanoutServer

    hint = {"port": 4711, "cap": 4}
    srv = FanoutServer(retention_budget=64, snapshot_hint=hint)
    try:
        srv.publish(b"x" * 400)
        srv.log.enforce_retention()
        with pytest.raises(SnapshotNeeded) as ei:
            srv.attach_peer("late", sink=lambda vs: 0, offset=0)
        assert ei.value.hint == hint
        assert ei.value.retained == (400 - 64, 400)
    finally:
        srv.close()
    bare = FanoutServer(retention_budget=64)
    try:
        bare.publish(b"x" * 400)
        bare.log.enforce_retention()
        with pytest.raises(SnapshotNeeded) as ei:
            bare.attach_peer("late", sink=lambda vs: 0, offset=0)
        assert ei.value.hint is None
    finally:
        bare.close()
