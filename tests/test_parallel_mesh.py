"""Sharded digest/Merkle pipeline on the virtual 8-device CPU mesh.

Exercises the same shard_map + collective code paths XLA emits for ICI on
real multi-chip hardware (conftest forces 8 virtual CPU devices).
"""

import hashlib

import jax
import numpy as np
import pytest

from dat_replication_protocol_tpu.ops import blake2b, merkle
from dat_replication_protocol_tpu.parallel import mesh as pmesh


def _digest(data: bytes) -> bytes:
    return hashlib.blake2b(data, digest_size=32).digest()


def test_make_mesh_rejects_non_power_of_two():
    with pytest.raises(ValueError, match="power of two"):
        pmesh.make_mesh(3)


def test_make_mesh_rejects_oversubscription():
    with pytest.raises(ValueError, match="devices"):
        pmesh.make_mesh(1024)


@pytest.mark.parametrize("ndev", [1, 2, 8])
def test_digest_root_step_matches_host(ndev):
    mesh = pmesh.make_mesh(ndev)
    payloads = [b"payload-%03d" % i * (i + 1) for i in range(16)]
    mh, ml, lengths = blake2b.pack_payloads(payloads)
    import jax.numpy as jnp

    leaf_hh, leaf_hl, root_hh, root_hl, total = pmesh.digest_root_step(
        mesh, jnp.asarray(mh), jnp.asarray(ml), jnp.asarray(lengths)
    )
    # leaf digests match hashlib, in submit order, across all shards
    got = merkle.digests_from_device(leaf_hh, leaf_hl)
    assert got == [_digest(p) for p in payloads]
    # global root matches the host tree over all leaves
    (dev_root,) = merkle.digests_from_device(root_hh, root_hl)
    assert dev_root == merkle.host_tree([_digest(p) for p in payloads])[-1][0]
    assert int(total) == sum(len(p) for p in payloads)


def test_sharded_diff_matches_host():
    mesh = pmesh.make_mesh(8)
    a = [_digest(b"leaf-%d" % i) for i in range(64)]
    b = list(a)
    changed = [0, 9, 33, 63]
    for i in changed:
        b[i] = _digest(b"changed-%d" % i)
    a_hh, a_hl = merkle.digests_to_device(a)
    b_hh, b_hl = merkle.digests_to_device(b)
    mask, (ra_hh, ra_hl), (rb_hh, rb_hl) = pmesh.sharded_diff(
        mesh, a_hh, a_hl, b_hh, b_hl
    )
    assert np.nonzero(np.asarray(mask))[0].tolist() == changed
    (root_a,) = merkle.digests_from_device(ra_hh, ra_hl)
    (root_b,) = merkle.digests_from_device(rb_hh, rb_hl)
    assert root_a == merkle.host_tree(a)[-1][0]
    assert root_b == merkle.host_tree(b)[-1][0]


def test_sharded_root_equals_single_device_root():
    # sharding must not change the tree shape: subtree-roots-then-top-tree
    # over p-o-2 shards is the same binary tree as the flat build
    a = [_digest(b"x%d" % i) for i in range(32)]
    hh, hl = merkle.digests_to_device([_digest(x) for x in a])
    r1_hh, r1_hl = merkle.root(hh, hl)
    mesh = pmesh.make_mesh(4)
    _, _, r8_hh, r8_hl, _ = pmesh.digest_root_step(
        mesh, *_packed(a)
    )
    assert merkle.digests_from_device(r1_hh, r1_hl) == merkle.digests_from_device(
        r8_hh, r8_hl
    )


def _packed(digests):
    import jax.numpy as jnp

    # hash the digest bytes themselves as payloads
    mh, ml, lengths = blake2b.pack_payloads(digests)
    return jnp.asarray(mh), jnp.asarray(ml), jnp.asarray(lengths)


def test_pad_batch_non_uniform_sizes():
    # round-3: the power-of-two shard precondition interacting with
    # padding (round-2 verdict "what's weak" #6) — a ragged batch size
    # must pad transparently and produce the same digests as the
    # unsharded hasher for the real items
    import hashlib

    import jax.numpy as jnp

    mesh = pmesh.make_mesh(8)
    payloads = [b"item-%d" % i * (i + 1) for i in range(21)]  # B=21 -> 8*4=32
    mh, ml, lengths = blake2b.pack_payloads(payloads)
    mh, ml, lengths, B = pmesh.pad_batch(
        mesh, jnp.asarray(mh), jnp.asarray(ml), jnp.asarray(lengths)
    )
    assert B == 21 and mh.shape[0] == 32
    leaf_hh, leaf_hl, root_hh, root_hl, total = pmesh.digest_root_step(
        mesh, mh, ml, lengths
    )
    got = merkle.digests_from_device(
        np.asarray(leaf_hh)[:B], np.asarray(leaf_hl)[:B]
    )
    exp = [hashlib.blake2b(p, digest_size=32).digest() for p in payloads]
    assert got == exp
    assert total == sum(len(p) for p in payloads)


def test_sharded_gear_scan_matches_single_device():
    # sequence-parallel CDC: sharded scan with the ppermute halo must be
    # bit-identical to the single-chip tiled scan over the same stream
    import random as pyrandom

    import jax.numpy as jnp

    from dat_replication_protocol_tpu.ops import rabin
    from dat_replication_protocol_tpu.parallel import cdc_mesh

    mesh = pmesh.make_mesh(8)
    stride = 1 << 10  # 1 KiB tiles
    T = 16  # 2 rows per chip
    data = pyrandom.Random(3).randbytes(T * stride)
    buf = np.frombuffer(data, dtype=np.uint8)
    payload = jnp.asarray(buf.reshape(T, stride).view("<u4"))

    bits = np.asarray(cdc_mesh.sharded_gear_scan(mesh, payload, avg_bits=8))

    # single-device reference through the same row layout
    got_cands = []
    for t in range(T):
        dense = np.nonzero(np.unpackbits(
            bits[t].view(np.uint8), bitorder="little"
        ))[0]
        local = dense - rabin.GROUP
        keep = (local >= 0) & (local < stride)
        got_cands.extend((local[keep] + t * stride).tolist())
    assert got_cands == rabin.host_candidates(data, 8)


def test_sharded_sketch_matches_single_device():
    import jax.numpy as jnp

    from dat_replication_protocol_tpu.parallel import make_mesh, sharded_sketch

    rng = np.random.default_rng(21)
    B, log2_slots = 203, 9  # deliberately NOT a multiple of the mesh
    rec_hh = jnp.asarray(rng.integers(0, 1 << 32, (B, 4), dtype=np.uint32))
    rec_hl = jnp.asarray(rng.integers(0, 1 << 32, (B, 4), dtype=np.uint32))
    slots = jnp.asarray(
        rng.integers(0, 1 << log2_slots, B, dtype=np.uint32)
    )
    mesh = make_mesh(8)
    got = sharded_sketch(mesh, rec_hh, rec_hl, slots, log2_slots)
    # single-device reference: the same wrapping scatter-add
    words = jnp.stack([rec_hl, rec_hh], axis=2).reshape(B, 8)
    want = jnp.zeros((1 << log2_slots, 8), jnp.uint32).at[
        slots.astype(jnp.int32)
    ].add(words)
    assert np.array_equal(np.asarray(got), np.asarray(want))


def test_sharded_hash_begin_matches_hashlib_across_buckets():
    """ISSUE 8: the hub's cross-session batch sharded over the mesh
    (batch-dim NamedSharding) — digests byte-identical to hashlib in
    submit order, across block-count buckets and non-multiple batch
    sizes (padding rows must not perturb real items)."""
    mesh = pmesh.make_mesh(8)
    payloads = (
        [b"tiny-%d" % i for i in range(5)]            # nblocks=1, B%8 != 0
        + [bytes([i]) * 300 for i in range(7)]        # nblocks=4 bucket
        + [b""]                                       # empty payload edge
    )
    collect = pmesh.sharded_hash_begin(mesh, payloads)
    collect.start_d2h()  # idempotent prefetch, same contract as ops
    got = collect()
    assert got == [hashlib.blake2b(p, digest_size=32).digest()
                   for p in payloads]
