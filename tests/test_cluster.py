"""Unit layer for the gossip mesh (ISSUE 15): the partition/link fault
axis, ReplicaNode semantics, the chaos-capable exchange engine, the
byzantine quarantine arms, churn/bootstrap, and the fleet-plane gossip
SLO.  The multi-seed chaos sweep lives in tests/test_cluster_faults.py.
"""

import dataclasses
import io
import json

import numpy as np
import pytest

from dat_replication_protocol_tpu.cluster import (
    ByzantineDivergence,
    ByzantineReplicaNode,
    ClusterSim,
    PeerQuarantined,
    ReplicaNode,
    classify_error,
    gossip_exchange,
)
from dat_replication_protocol_tpu.fanout.log import SnapshotNeeded
from dat_replication_protocol_tpu.obs import fleet
from dat_replication_protocol_tpu.session.faults import (
    FaultPlan,
    TransportFault,
)
from dat_replication_protocol_tpu.wire.framing import ProtocolError


def recs(lo, hi, tag="s", val=b"v"):
    return [{"key": f"k{i}", "change": i, "from": 0, "to": 1,
             "value": val + b"%d" % i, "subset": tag}
            for i in range(lo, hi)]


# -- partition/link axis (satellite: FaultPlan.for_sweep) --------------------


def test_for_sweep_default_path_golden_byte_identical():
    """The pre-axis generator reproduces EXACTLY: these tuples were
    captured from the generator before the partition axis landed —
    existing 1:1 and per-session sweeps must replay unchanged."""
    golden = {
        (0, 1000, 0): (827307999, 64, None, None, None, 255, 988,
                       0.02, 0.0, 0.001),
        (1, 1000, 0): (687482608, 1024, None, 472, None, 255, None,
                       0.0, 0.0, 0.001),
        (2, 5000, 1): (1042467055, 1024, None, None, None, 255, 1043,
                       0.02, 0.05, 0.001),
        (7, 1234, 0): (324967622, None, None, 1193, None, 255, None,
                       0.0, 0.0, 0.001),
        (13, 64, 2): (845453773, 64, None, None, None, 255, None,
                      0.0, 0.0, 0.001),
        (5, 999, 1): (250431313, None, None, 551, None, 255, None,
                      0.0, 0.0, 0.001),
    }
    for (seed, wl, att), want in golden.items():
        got = dataclasses.astuple(FaultPlan.for_sweep(seed, wl, att))
        assert got == want, (seed, wl, att)
    # the per-session axis is untouched too
    assert dataclasses.astuple(
        FaultPlan.for_sweep(3, 2048, 0, session=2, n_sessions=4)) == \
        (438892869, 7, None, None, None, 255, None, 0.0, 0.0, 0.0005)


def test_partition_scenario_partitions_the_replica_range():
    for seed in range(8):
        for n in (2, 4, 16, 64):
            sc = FaultPlan.partition_scenario(seed, n)
            a, b = sc["groups"]
            assert a | b == frozenset(range(n))
            assert not (a & b)
            assert a and b  # a real cut: both sides populated
            assert 1 <= sc["cut_round"] < sc["heal_round"]
            # deterministic: the generator IS the ground truth
            assert sc == FaultPlan.partition_scenario(seed, n)


def test_cluster_plans_cut_cross_group_links_and_heal():
    seed, n = 9, 16
    sc = FaultPlan.partition_scenario(seed, n)
    minority = sc["groups"][0]
    a = next(iter(minority))
    b = next(iter(sc["groups"][1]))
    during = FaultPlan.for_sweep(seed, 1000, link=(a, b), n_replicas=n,
                                 gossip_round=sc["cut_round"])
    assert during.drop_at == 0  # the dial itself fails
    after = FaultPlan.for_sweep(seed, 1000, link=(a, b), n_replicas=n,
                                gossip_round=sc["heal_round"])
    assert after.drop_at != 0  # healed (any later fault is the link's
    # own scheduled scenario, not the partition)
    # intra-group links never see the cut
    c, d = sorted(sc["groups"][1])[:2]
    intra = FaultPlan.for_sweep(seed, 1000, link=(c, d), n_replicas=n,
                                gossip_round=sc["cut_round"])
    assert intra.drop_at is None or intra.drop_at > 0


def test_link_scenario_deterministic_and_order_free():
    s1 = FaultPlan.link_scenario(5, 8, (1, 3))
    assert s1 == FaultPlan.link_scenario(5, 8, (3, 1))
    assert s1[0] in FaultPlan.LINK_SCENARIOS
    assert 1 <= s1[1] < 8


# -- ReplicaNode --------------------------------------------------------------


def test_content_digest_is_order_and_duplicate_free():
    a = ReplicaNode("a", recs(0, 10))
    b = ReplicaNode("b", list(reversed(recs(0, 10))))
    assert a.content_digest() == b.content_digest()
    # duplicate frames do not change identity
    b.absorb(recs(3, 7))
    assert a.content_digest() == b.content_digest()
    assert b.record_count == 10


def test_absent_optionals_survive_gossip_byte_exactly():
    """Records WITHOUT subset/value must keep their canonical digests
    through an exchange — repairs travel as byte-preserving wire, so
    absent-vs-present-empty never forks the digest set (materializing
    rows would collapse absent to '' and the mesh would re-reconcile
    the same records forever)."""
    bare = [{"key": f"n{i}", "change": i, "from": 0, "to": 1}
            for i in range(6)]
    a = ReplicaNode("a", bare + recs(0, 4))
    b = ReplicaNode("b", recs(0, 4))
    gossip_exchange(a, b)
    assert a.content_digest() == b.content_digest()
    # and a second exchange finds ZERO divergence (the digests agreed)
    out = gossip_exchange(a, b)
    assert out["diff"] == 0


def test_checkpoint_restore_roundtrip():
    a = ReplicaNode("a", recs(0, 12), fanout_retention=1 << 14)
    a.round = 7
    ckpt = a.checkpoint()
    back = ReplicaNode.from_checkpoint(ckpt, fanout_retention=1 << 14)
    assert back.key == "a"
    assert back.round == 7
    assert back.content_digest() == a.content_digest()
    assert back.log_gen == 1  # a restart is a new feed generation


def test_replica_key_validation():
    with pytest.raises(ValueError):
        ReplicaNode("bad{key}")
    with pytest.raises(ValueError):
        ReplicaNode("")


# -- the exchange engine ------------------------------------------------------


def test_exchange_converges_and_wire_tracks_diff():
    big = recs(0, 400)
    a = ReplicaNode("a", big + recs(1000, 1004, tag="u"))
    b = ReplicaNode("b", big)
    out = gossip_exchange(a, b)
    assert a.content_digest() == b.content_digest()
    # O(diff) headline: a 4-record diff over a 400-record set moves a
    # small fraction of the full-transfer wire
    full = len(a.canonical_wire())
    assert out["wire_bytes"] < full
    assert out["diff"] == 4


def test_exchange_truncation_is_transport_class_and_stateless():
    a = ReplicaNode("a", recs(0, 20))
    b = ReplicaNode("b", recs(10, 30))
    da, db = a.content_digest(), b.content_digest()
    plan = FaultPlan(seed=1, truncate_at=40)
    with pytest.raises(TransportFault):
        gossip_exchange(a, b, plan_out=plan)
    # no state change on either side — the no-partial-apply contract
    assert a.content_digest() == da
    assert b.content_digest() == db
    assert classify_error(TransportFault("x")) == "transport"


def test_exchange_flip_is_one_structured_error():
    a = ReplicaNode("a", recs(0, 20))
    b = ReplicaNode("b", recs(10, 30))
    da, db = a.content_digest(), b.content_digest()
    # flip inside the first symbols payload: the codec (or the peel
    # checksums) must refuse — never a wrong diff
    plan = FaultPlan(seed=2, flip_at=30, flip_mask=0x40)
    with pytest.raises(ProtocolError) as ei:
        gossip_exchange(a, b, plan_out=plan)
    assert classify_error(ei.value) == "corruption"
    assert a.content_digest() == da
    assert b.content_digest() == db


def test_quarantine_needs_repeated_corruption():
    a = ReplicaNode("a", byzantine_after=2)
    err = ProtocolError("corrupt", offset=3)
    assert a.note_corruption("p", err) is None  # first: the wire
    assert a.note_corruption("p", err) is not None  # second: a liar
    assert a.is_quarantined("p")
    with pytest.raises(PeerQuarantined) as ei:
        a.refuse_if_quarantined("p")
    assert ei.value.peer == "p"


def test_suspicion_is_cumulative_not_laundered_by_success():
    """A byzantine replica that lies only when its content is
    requested (the wrong-chunk shape) interleaves clean exchanges with
    corrupt ones — suspicion must accumulate anyway."""
    a = ReplicaNode("a", byzantine_after=2)
    err = ProtocolError("corrupt")
    assert a.note_corruption("p", err) is None
    a.note_success("p")  # a clean exchange in between launders nothing
    assert a.note_corruption("p", err) is not None
    assert a.is_quarantined("p")


def test_sampling_skips_quarantined_peers():
    a = ReplicaNode("a", byzantine_after=1)
    a.note_corruption("bad", ProtocolError("corrupt"))
    picks = {a.sample_peer(["a", "bad", "good"]) for _ in range(20)}
    assert picks == {"good"}


# -- byzantine arms (satellite: quarantine coverage) -------------------------


def _byz_sim(arm, **kw):
    return ClusterSim(4, seed=5, chaos=False, byzantine=1,
                      byzantine_arm=arm, byzantine_after=1, **kw)


def test_byzantine_wrong_symbol_one_error_quarantine_rest_converge():
    sim = _byz_sim("wrong-symbol")
    out = sim.run()
    # injector ground truth: links are CLEAN, so every corrupt
    # exchange involves the byzantine replica, and each such exchange
    # surfaced exactly ONE structured error
    corrupt = [ex for ev in sim.events for ex in ev["exchanges"]
               if ex["outcome"] == "corruption"]
    assert corrupt
    for ex in corrupt:
        assert "r1" in (ex["initiator"], ex["responder"])
        assert ex["error"] is not None
    assert any(q["peer"] == "r1" for q in out["quarantines"])
    assert out["converged"]
    healthy = {sim.nodes[k].content_digest() for k in sim.healthy()}
    assert len(healthy) == 1


def test_byzantine_wrong_chunk_digest_detected_at_apply():
    sim = _byz_sim("wrong-chunk")
    out = sim.run()
    q = [q for q in out["quarantines"] if q["peer"] == "r1"]
    assert q and all(x["arm"] == "wrong-chunk-digest" for x in q)
    assert out["converged"]


def test_byzantine_ack_regression_quarantined_by_owner():
    owner = ReplicaNode("owner", recs(0, 8), fanout_retention=1 << 14)
    byz = ByzantineReplicaNode("byz", (), arm="ack-regression")
    owner.publish_repairs(owner.canonical_wire())
    byz.drain_feed(owner)  # first ack: honest frontier
    owner.publish_repairs(ReplicaNode("t", recs(8, 12)).canonical_wire())
    with pytest.raises(ByzantineDivergence) as ei:
        byz.drain_feed(owner)
    assert ei.value.arm == "ack-regression"
    assert ei.value.peer == "byz"
    assert ei.value.offset is not None
    assert owner.is_quarantined("byz")


def test_byzantine_feed_corrupt_quarantined_by_follower():
    owner = ByzantineReplicaNode("byz", recs(0, 8), arm="feed-corrupt",
                                 fanout_retention=1 << 14)
    follower = ReplicaNode("f", ())
    d0 = follower.content_digest()
    owner.publish_repairs(owner.canonical_wire())
    with pytest.raises(ByzantineDivergence) as ei:
        follower.drain_feed(owner)
    assert ei.value.arm == "feed-corrupt"
    assert ei.value.peer == "byz"
    assert follower.is_quarantined("byz")
    # nothing absorbed: corruption is never a partial apply
    assert follower.content_digest() == d0


def test_byzantine_divergence_is_structured():
    e = ByzantineDivergence("msg", peer="p", arm="wrong-symbol",
                            frame=3, offset=99)
    assert e.peer == "p" and e.frame == 3 and e.offset == 99
    assert "frame=3" in str(e) and "byte=99" in str(e)
    assert isinstance(e, ProtocolError)


# -- churn / flash crowd / bootstrap -----------------------------------------


def test_churn_restart_resumes_from_checkpoint_and_converges():
    sim = ClusterSim(8, seed=5, chaos=True, churn=True)
    out = sim.run()
    assert out["converged"] and out["rounds"] <= out["bound"]
    crashed = [ev["churn"] for ev in sim.events
               if ev["churn"] and "crashed" in ev["churn"]]
    restarted = [ev["churn"] for ev in sim.events
                 if ev["churn"] and "restarted" in ev["churn"]]
    assert crashed and restarted


def test_trim_past_follower_bootstraps_over_snapshot_protocol():
    """A restarted replica whose feed cursor fell below the broadcast
    retention window recovers over the PR 12 snapshot protocol — the
    SnapshotNeeded -> bootstrap arm, not a silent short read."""
    sim = ClusterSim(8, seed=0, chaos=True, churn=True, fanout=True,
                     fanout_retention=512, records_per=32, divergence=8)
    out = sim.run()
    assert out["bootstraps"], "retention budget never trimmed a laggard"
    assert out["converged"] and out["rounds"] <= out["bound"]


def test_flash_crowd_joins_cold_and_converges():
    sim = ClusterSim(8, seed=11, chaos=True, flash_crowd=3)
    out = sim.run()
    joined = [j for ev in sim.events for j in ev["joined"]]
    assert len(joined) == 3
    assert all(j["wire_bytes"] > 0 for j in joined)
    assert out["converged"]
    # the joiners ended byte-identical to the seed replicas
    assert len(set(out["digests"].values())) == 1


def test_snapshot_needed_surfaces_structured_from_log():
    owner = ReplicaNode("o", recs(0, 64), fanout_retention=256)
    follower = ReplicaNode("f", ())
    for i in range(6):
        owner.publish_repairs(
            ReplicaNode("t", recs(i * 10, i * 10 + 10)).canonical_wire())
        owner.log.enforce_retention()
    with pytest.raises(SnapshotNeeded):
        follower.drain_feed(owner)
    res = follower.bootstrap_from(owner)
    assert res["wire_bytes"] > 0
    assert follower.stats["bootstraps"] == 1


# -- determinism --------------------------------------------------------------


def test_sim_is_deterministic_per_seed():
    outs = []
    for _ in range(2):
        sim = ClusterSim(8, seed=13, chaos=True, churn=True, fanout=True)
        outs.append(sim.run())
    assert outs[0]["digests"] == outs[1]["digests"]
    assert outs[0]["rounds"] == outs[1]["rounds"]
    assert outs[0]["wire_bytes"] == outs[1]["wire_bytes"]
    assert outs[0]["quarantines"] == outs[1]["quarantines"]


# -- fleet-plane gossip SLO (tentpole: convergence observable live) ----------


def _targets(sim):
    def target(key):
        node = sim.nodes[key]
        return lambda: {"ts": 0.0,
                        "watermarks": {"monotonic": 0.0, "links": {}},
                        "gossip": node.snapshot()}

    return [fleet.FleetTarget(target(k), name=k) for k in sim.nodes]


def _slo_file(tmp_path, slo):
    path = tmp_path / "slo.json"
    path.write_text(json.dumps(slo))
    return str(path)


def test_fleet_gossip_slo_passes_on_converged_mesh(tmp_path):
    sim = ClusterSim(4, seed=2, chaos=False)
    assert sim.run()["converged"]
    slo = _slo_file(tmp_path, {"gossip": {"require_converged": True,
                                          "max_rounds_behind": 2,
                                          "max_quarantined": 0}})
    buf = io.StringIO()
    assert fleet.run_fleet_check(_targets(sim), slo, polls=1,
                                 out=buf) == 0, buf.getvalue()
    assert "gossip.require_converged" in buf.getvalue()


def test_fleet_gossip_slo_fails_on_divergence(tmp_path):
    sim = ClusterSim(4, seed=2, chaos=False)
    sim.run()
    sim.nodes["r0"].absorb(
        [{"key": "rogue", "change": 1, "from": 0, "to": 1, "value": b"z"}])
    slo = _slo_file(tmp_path, {"gossip": {"require_converged": True}})
    buf = io.StringIO()
    assert fleet.run_fleet_check(_targets(sim), slo, polls=1,
                                 out=buf) == 1
    assert "distinct content digests" in buf.getvalue()


def test_fleet_gossip_rounds_behind_column(tmp_path):
    """Rounds-behind is PROGRESS behind the fleet frontier since first
    sight, not absolute position — live round counters are lifetime
    values on unsynchronized processes, so a restarted (low-counter)
    replica must read 0, and only a replica whose timer stops
    advancing with the fleet reads behind."""
    sim = ClusterSim(4, seed=2, chaos=False)
    sim.run()
    # a freshly restarted replica: tiny lifetime counter, converged
    sim.nodes["r2"].round = 1
    targets = _targets(sim)
    view = fleet.FleetView(targets)
    view.poll()  # baseline
    for k in ("r0", "r1", "r2"):  # r3's timer stops advancing
        sim.nodes[k].round += 3
    sample = view.poll()
    assert sample["gossip"]["r3"]["rounds_behind"] == 3
    assert sample["gossip"]["r0"]["rounds_behind"] == 0
    assert sample["gossip"]["r2"]["rounds_behind"] == 0  # restart-proof
    frame = fleet.render_dashboard(view, sample)
    assert "behind" in frame and "r3" in frame
    # the SLO gate breaches on the stuck replica across its own polls
    def advancing(key):
        node = sim.nodes[key]

        def snap():
            if key != "r3":
                node.round += 3
            return {"ts": 0.0,
                    "watermarks": {"monotonic": 0.0, "links": {}},
                    "gossip": node.snapshot()}

        return snap

    slo = _slo_file(tmp_path, {"gossip": {"max_rounds_behind": 2}})
    buf = io.StringIO()
    assert fleet.run_fleet_check(
        [fleet.FleetTarget(advancing(k), name=k) for k in sim.nodes],
        slo, polls=2, interval=0.01, out=buf) == 1
    assert "behind the fleet frontier" in buf.getvalue()


@pytest.mark.parametrize("bad", [
    {"gossip": {}},
    {"gossip": {"unknown_key": 1}},
    {"gossip": {"max_rounds_behind": "two"}},
    {"gossip": {"require_converged": "yes"}},
    {"gossip": 3},
])
def test_fleet_gossip_slo_malformed_shapes_are_loud(tmp_path, bad):
    path = _slo_file(tmp_path, bad)
    with pytest.raises(ValueError):
        fleet.load_slo(path)


def test_fleet_gossip_slo_no_targets_is_a_failure(tmp_path):
    slo = _slo_file(tmp_path, {"gossip": {"require_converged": True}})
    targets = [fleet.FleetTarget(
        lambda: {"ts": 0.0, "watermarks": {"monotonic": 0.0,
                                           "links": {}}}, name="t")]
    buf = io.StringIO()
    assert fleet.run_fleet_check(targets, slo, polls=1, out=buf) == 1
    assert "no targets report gossip" in buf.getvalue()


# -- quarantine provenance through the fleet plane (ISSUE 19 satellite) ------


@pytest.mark.parametrize("arm,want_arm", [
    ("wrong-symbol", "wrong-symbol"),
    ("wrong-chunk", "wrong-chunk-digest"),
])
def test_fleet_reports_quarantine_provenance_matching_injector(arm,
                                                               want_arm):
    """The fleet join's per-replica ``quarantine`` record must equal
    the injector's own ground truth — WHO was cut, on WHICH arm, at
    which frame/offset — straight from each node's structured
    :class:`ByzantineDivergence`, at every poll."""
    sim = _byz_sim(arm)
    out = sim.run()
    assert out["converged"]
    view = fleet.FleetView(_targets(sim))
    for _ in range(2):  # provenance is stable across polls
        sample = view.poll()
        reporting = 0
        for tname, row in sample["gossip"].items():
            node = sim.nodes[tname]
            truth = {peer: {"arm": d.arm, "frame": d.frame,
                            "offset": d.offset}
                     for peer, d in node.quarantined.items()}
            assert row["quarantine"] == truth, tname
            assert row["suspicion"] == dict(node._suspect), tname
            if tname == sim.byzantine_key:
                continue  # the liar also cuts honest peers it framed
            if truth:
                reporting += 1
                assert set(truth) == {"r1"}, \
                    "honest replicas cut only the liar on clean links"
                assert all(v["arm"] == want_arm for v in truth.values())
        assert reporting, "nobody quarantined the byzantine replica"
    # the dashboard renders the provenance line when the mesh section
    # is present (a mesh sample forces the section)
    sample["mesh"] = {"pairs": {}, "exchange_p99_s": None,
                      "exchange_count": 0}
    frame = fleet.render_dashboard(view, sample)
    assert f"arm={want_arm}" in frame
    assert "quarantine" in frame


# -- mesh convergence SLO against a live in-process mesh (tier-1 gate) -------


def test_fleet_check_mesh_slo_on_in_process_mesh(obs_enabled, tmp_path):
    """The ISSUE 19 live gate: ``obs fleet --check`` with the four
    mesh SLO keys over a 3-replica in-process mesh that gossiped LIT —
    per-pair divergence exactly 0, every link fresh, p99 bounded."""
    from dat_replication_protocol_tpu.obs.propagation import PROPAGATION

    sim = ClusterSim(3, seed=7, records_per=6, divergence=2, chaos=False)
    assert sim.run()["converged"]

    def target(key):
        node = sim.nodes[key]
        return lambda: {"ts": 0.0,
                        "watermarks": {"monotonic": 0.0, "links": {}},
                        "gossip": node.snapshot(),
                        "propagation": PROPAGATION.snapshot()}

    targets = [fleet.FleetTarget(target(k), name=k) for k in sim.nodes]
    slo = _slo_file(tmp_path, {"gossip": {
        "require_converged": True,
        "max_convergence_rounds": fleet.mesh_rounds_floor(3),
        "max_divergence_bytes": 0,
        "max_exchange_age_s": 120.0,
        "max_exchange_p99_s": 30.0,
    }})
    buf = io.StringIO()
    assert fleet.run_fleet_check(targets, slo, polls=1,
                                 out=buf) == 0, buf.getvalue()
    text = buf.getvalue()
    assert "gossip.max_convergence_rounds" in text
    assert "divergence exactly 0" in text
    assert "gossip.max_exchange_p99_s" in text
    # the same SLO against a DARK mesh fails loudly: a plane nobody
    # reports is indistinguishable from a broken one
    dark = _targets(sim)
    buf = io.StringIO()
    assert fleet.run_fleet_check(dark, slo, polls=1, out=buf) == 1
    assert "no targets report propagation records" in buf.getvalue()


# -- live mode: sidecar --replica over real TCP ------------------------------


def test_live_replica_mesh_converges_over_tcp():
    """Three --replica-shaped sidecars (serve_tcp responder loop + a
    GossipDriver each) converge from three-way divergence over real
    sockets — the ``--replica``/``--gossip-peers`` deployment shape,
    in-process."""
    import threading

    from dat_replication_protocol_tpu import sidecar
    from dat_replication_protocol_tpu.cluster import GossipDriver

    nodes = {
        "n1": ReplicaNode("n1", recs(0, 30)),
        "n2": ReplicaNode("n2", recs(20, 50)),
        "n3": ReplicaNode("n3", recs(40, 70)),
    }
    ports = {}
    for name, node in nodes.items():
        evt = threading.Event()
        threading.Thread(
            target=sidecar.serve_tcp, args=("127.0.0.1", 0),
            kwargs=dict(
                ready_cb=lambda p, name=name, evt=evt: (
                    ports.__setitem__(name, p), evt.set()),
                replica_node=node, max_sessions=500),
            daemon=True).start()
        assert evt.wait(10)
    drivers = [
        GossipDriver(nodes[me],
                     [f"127.0.0.1:{ports[o]}" for o in nodes if o != me],
                     interval=0.05, seed=i).start()
        for i, me in enumerate(nodes)
    ]
    import time
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            digests = {n.content_digest() for n in nodes.values()}
            if len(digests) == 1:
                break
            time.sleep(0.05)
        assert len({n.content_digest() for n in nodes.values()}) == 1, \
            "live mesh did not converge"
        assert nodes["n1"].record_count == 70
        # the stats record --stats-fd / /snapshot carries
        snap = drivers[0].snapshot()
        assert snap["replica"] == "n1"
        assert snap["rounds"] >= 1 and "peers" in snap
    finally:
        for d in drivers:
            d.close()


def test_sidecar_replica_flag_wiring():
    """--replica mode parses, loads an absent file as a cold replica,
    and refuses the invalid combinations."""
    import tempfile

    from dat_replication_protocol_tpu import sidecar

    node = sidecar.load_replica_node("/nonexistent/cold.log", "cold")
    assert node.record_count == 0
    with tempfile.NamedTemporaryFile(suffix=".log") as f:
        f.write(ReplicaNode("t", recs(0, 5)).canonical_wire())
        f.flush()
        node = sidecar.load_replica_node(f.name, "warm")
        assert node.record_count == 5
    for argv in (
        ["--stdio", "--replica", "x.log"],
        ["--tcp", "127.0.0.1:0", "--replica", "x.log", "--hub"],
        ["--tcp", "127.0.0.1:0", "--replica", "x.log", "--reconcile",
         "y.log"],
        ["--tcp", "127.0.0.1:0", "--gossip-peers", "h:1"],
    ):
        with pytest.raises(SystemExit):
            sidecar.main(argv)


def test_snapshot_stats_carries_gossip_record():
    from dat_replication_protocol_tpu import sidecar

    node = ReplicaNode("stats-probe", recs(0, 3))
    sidecar.set_active_gossip(node)
    try:
        snap = sidecar.snapshot_stats()
        assert snap["gossip"]["replica"] == "stats-probe"
        assert snap["gossip"]["records"] == 3
        assert "digest" in snap["gossip"]
    finally:
        sidecar.set_active_gossip(None)
    assert "gossip" not in sidecar.snapshot_stats()


def test_delivered_form_replica_converges_on_absent_optionals():
    """The live mesh's record identity is the DELIVERED
    materialization (absent optionals as ''/b'') — a live replica in
    wire form would re-reconcile absent-field records against its
    peers forever (ship -> materialize -> re-encode changes identity).
    ``load_replica_node`` replicas must reach diff 0 over the real
    record-materializing drivers."""
    import socket
    import threading

    from dat_replication_protocol_tpu.cluster import (
        serve_responder_session,
    )
    from dat_replication_protocol_tpu.runtime.reconcile_driver import (
        run_initiator,
    )

    bare = [{"key": f"n{i}", "change": i, "from": 0, "to": 1}
            for i in range(6)]
    a = ReplicaNode("a", bare + recs(0, 4), delivered_form=True)
    b = ReplicaNode("b", recs(0, 4), delivered_form=True)

    def once():
        sa, sb = socket.socketpair()
        t = threading.Thread(target=lambda: serve_responder_session(
            b, sb.recv, sb.sendall,
            close_write=lambda: sb.shutdown(socket.SHUT_WR)))
        t.start()
        st = run_initiator(a.replica, sa.recv, sa.sendall,
                           close_write=lambda: sa.shutdown(
                               socket.SHUT_WR))
        t.join(10)
        if st["received"]:
            a.absorb(st["received"])
        return st

    once()
    assert a.content_digest() == b.content_digest()
    again = once()  # and the mesh is DONE: diff 0, nothing re-ships
    assert again["records_sent"] == 0 and not again["received"]
    # checkpoint/restore keeps the mode
    back = ReplicaNode.from_checkpoint(a.checkpoint())
    assert back.delivered_form
    assert back.content_digest() == a.content_digest()
