"""The event-driven edge (ISSUE 17): ONE epoll session table.

Every test here is the threaded sidecar test restated against
:class:`~dat_replication_protocol_tpu.edge.EdgeLoop` — same foreign
clients (raw wire bytes from test_wire_fixtures), same structured
record shapes, same staged-overload ladder — proving the C10k rewrite
changed the mechanism and nothing observable.
"""

import hashlib
import socket
import threading
import time

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.edge import EdgeLoop, QOS_PRESETS, \
    serve_edge
from dat_replication_protocol_tpu.hub import ReplicationHub

from test_wire_fixtures import CHANGE_PAYLOAD, SESSION_1, SESSION_4


def _decode_reply(raw: bytes) -> list:
    out = []
    dec = protocol.decode()
    dec.change(lambda ch, done: (out.append(ch), done()))
    dec.write(raw)
    dec.end()
    assert dec.finished
    return out


def _recv_all(sock: socket.socket) -> bytes:
    parts = []
    while True:
        d = sock.recv(65536)
        if not d:
            return b"".join(parts)
        parts.append(d)


def _start_loop(loop: EdgeLoop) -> tuple:
    """Bind + serve on a thread; returns (port, thread)."""
    port = loop.bind("127.0.0.1", 0)
    t = threading.Thread(target=loop.serve, daemon=True)
    t.start()
    return port, t


def test_edge_serves_reference_transcript_session_1():
    hub = ReplicationHub(linger_s=0.002)
    loop = EdgeLoop(hub, max_sessions=1)
    try:
        port, t = _start_loop(loop)
        c = socket.create_connection(("127.0.0.1", port), timeout=10)
        c.sendall(SESSION_1)
        c.shutdown(socket.SHUT_WR)
        reply = _decode_reply(_recv_all(c))
        c.close()
        t.join(timeout=10)
        assert not t.is_alive()
    finally:
        hub.close()
    assert len(reply) == 1
    ch = reply[0]
    assert ch.key == "change-0" and ch.subset == "digest:change"
    assert ch.value == hashlib.blake2b(
        CHANGE_PAYLOAD, digest_size=32).digest()


def test_edge_blob_and_change_session_4():
    hub = ReplicationHub(linger_s=0.002)
    loop = EdgeLoop(hub, max_sessions=1)
    try:
        port, t = _start_loop(loop)
        c = socket.create_connection(("127.0.0.1", port), timeout=10)
        c.sendall(SESSION_4)
        c.shutdown(socket.SHUT_WR)
        reply = _decode_reply(_recv_all(c))
        c.close()
        t.join(timeout=10)
    finally:
        hub.close()
    by_key = {ch.key: ch for ch in reply}
    assert set(by_key) == {"blob-0", "change-0"}
    assert by_key["blob-0"].value == hashlib.blake2b(
        b"hello world", digest_size=32).digest()
    assert by_key["blob-0"].subset == "digest:blob"
    assert by_key["change-0"].value == hashlib.blake2b(
        CHANGE_PAYLOAD, digest_size=32).digest()


def test_edge_protocol_error_closes_connection():
    """Hostile bytes observe the destroy cascade + EOF — never a hang,
    and the loop survives to serve the NEXT session cleanly (the
    neighbor-isolation half of the contract)."""
    hub = ReplicationHub(linger_s=0.002)
    loop = EdgeLoop(hub, max_sessions=2)
    try:
        port, t = _start_loop(loop)
        c = socket.create_connection(("127.0.0.1", port), timeout=10)
        c.settimeout(15)
        c.sendall(b"\xff" * 64)  # hostile length varint
        assert _recv_all(c) == b""
        c.close()
        # the loop is still alive: a clean session completes after it
        c2 = socket.create_connection(("127.0.0.1", port), timeout=10)
        c2.sendall(SESSION_1)
        c2.shutdown(socket.SHUT_WR)
        reply = _decode_reply(_recv_all(c2))
        c2.close()
        t.join(timeout=10)
        assert len(reply) == 1 and reply[0].key == "change-0"
    finally:
        hub.close()


def test_edge_hub_busy_rejection_is_structured(obs_enabled):
    """Overload stage 1 through the loop: past the hub's admission
    bound the client observes EOF with no reply bytes, the edge counts
    the rejection, and the hub's structured reject event fires — the
    threaded leg's record, byte-for-byte.  The rejection shows up as
    the loop's LABELED registry counter (collector-backed, read off
    the admission attributes) cross-checked against
    ``admission_state()`` — the ISSUE 18 satellite: the fleet
    ``max_rejected`` ceiling reads the registry, so the count must be
    there with the loop live, gate or no gate."""
    from dat_replication_protocol_tpu.obs.events import EVENTS

    hub = ReplicationHub(max_sessions=1)
    held = hub.register("occupant")
    # max_sessions=2 keeps the loop ALIVE after the rejection: the
    # collector unregisters at shutdown, so the registry cross-check
    # below must sample a live loop (the fleet poller's view)
    loop = EdgeLoop(hub, max_sessions=2)
    try:
        port, t = _start_loop(loop)
        c = socket.create_connection(("127.0.0.1", port), timeout=10)
        c.settimeout(15)
        c.sendall(SESSION_1)
        assert _recv_all(c) == b""  # EOF, no decoder, no reply
        c.close()
        deadline = time.monotonic() + 5
        while (loop.admission_state()["rejected"] < 1
                and time.monotonic() < deadline):
            time.sleep(0.01)
        snap = loop.snapshot()
        assert snap["rejected"] == 1 and snap["admitted"] == 0
        recs = [e["fields"] for e in EVENTS.events("sidecar.session")]
        assert recs and recs[-1] == {
            "changes": 0, "blobs": 0, "bytes": 0, "digests": 0,
            "ok": False, "rejected": True, "sessions": 1,
            "parked_bytes": 0}
        name = loop.profiler.name
        counters = obs_enabled.REGISTRY.snapshot()["counters"]
        assert counters[f"edge.rejected{{loop={name}}}"] == 1
        assert counters[f"edge.served{{loop={name}}}"] == 1
        assert counters[f"edge.admitted{{loop={name}}}"] == 0
        assert counters[f"edge.shed{{loop={name}}}"] == 0
        state = loop.admission_state()
        assert state["rejected"] == 1 and state["shed"] == 0
        held.close()
        loop.close()
        t.join(timeout=10)
        assert not t.is_alive()
    finally:
        hub.close()


def test_edge_concurrent_sessions_one_loop(obs_enabled):
    """N concurrent mixed-QoS hub sessions through ONE loop thread:
    every reply byte-exact, the session-table snapshot carries the
    per-class breakdown while they are live, and the per-class gauges
    ride the registry collector (the fleet-plane satellite)."""
    from dat_replication_protocol_tpu.obs import metrics as obs_metrics

    N = 8
    hub = ReplicationHub(linger_s=0.002)
    qos_of = lambda n, peer, mode: \
        "latency" if n % 2 else "throughput"  # noqa: E731
    loop = EdgeLoop(hub, qos_of=qos_of, max_sessions=N)
    hold = threading.Event()
    results = {}

    def client(i):
        c = socket.create_connection(("127.0.0.1", port), timeout=10)
        half = len(SESSION_4) // 2
        c.sendall(SESSION_4[:half])
        hold.wait(10)  # keep every session parked in the table at once
        c.sendall(SESSION_4[half:])
        c.shutdown(socket.SHUT_WR)
        results[i] = _decode_reply(_recv_all(c))
        c.close()

    try:
        port, t = _start_loop(loop)
        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(N)]
        for th in threads:
            th.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            snap = loop.snapshot()
            if snap["sessions"] == N:
                break
            time.sleep(0.01)
        snap = loop.snapshot()
        assert snap["sessions"] == N
        assert snap["by_class"] == {"latency": N // 2,
                                    "throughput": N // 2}
        assert snap["by_kind"] == {"hub": N}
        reg = obs_metrics.snapshot()
        assert reg["gauges"]["edge.sessions"] == float(N)
        assert reg["gauges"]["edge.sessions{class=latency}"] == N // 2
        adm = loop.admission_state()
        assert adm["stage"] == "edge" and adm["open"] is True
        assert adm["hub"]["sessions"] == N
        hold.set()
        for th in threads:
            th.join(15)
            assert not th.is_alive(), "client HANG"
        t.join(timeout=10)
    finally:
        hold.set()
        hub.close()
    blob_digest = hashlib.blake2b(b"hello world", digest_size=32).digest()
    for i in range(N):
        by_key = {ch.key: ch for ch in results[i]}
        assert set(by_key) == {"blob-0", "change-0"}, f"client {i}"
        assert by_key["blob-0"].value == blob_digest


def test_edge_fanout_broadcasts_source_wire_to_subscribers():
    """The --fanout shape through the loop: first connection claims the
    source slot (decoded + digested once), later connections subscribe
    and receive the source's wire byte-exactly — including a late
    joiner served from retention after seal."""
    from dat_replication_protocol_tpu.fanout import FanoutServer

    hub = ReplicationHub(linger_s=0.002)
    fanout = FanoutServer(stall_timeout=10.0)
    loop = EdgeLoop(hub, fanouts={"main": fanout}, max_sessions=3)
    try:
        port, t = _start_loop(loop)
        addr = ("127.0.0.1", port)
        src = socket.create_connection(addr, timeout=10)
        half = len(SESSION_4) // 2
        src.sendall(SESSION_4[:half])
        time.sleep(0.2)  # the claim lands before the subscriber dials
        sub1 = socket.create_connection(addr, timeout=10)
        src.sendall(SESSION_4[half:])
        src.shutdown(socket.SHUT_WR)
        reply = _decode_reply(_recv_all(src))
        src.close()
        by_key = {ch.key: ch for ch in reply}
        assert set(by_key) == {"blob-0", "change-0"}  # digested at source
        sub2 = socket.create_connection(addr, timeout=10)  # late joiner
        got1 = _recv_all(sub1)
        got2 = _recv_all(sub2)
        sub1.close()
        sub2.close()
        t.join(timeout=10)
        assert got1 == SESSION_4  # byte-exact broadcast
        assert got2 == SESSION_4
    finally:
        fanout.close()
        hub.close()


def test_edge_one_hub_serves_n_broadcast_groups():
    """The tentpole's unified-table claim: ONE loop + ONE hub serving
    TWO broadcast groups at once — each group's source digested by the
    shared hub, each group's subscriber byte-exact on ITS OWN wire."""
    from dat_replication_protocol_tpu.fanout import FanoutServer

    hub = ReplicationHub(linger_s=0.002)
    f_a = FanoutServer(stall_timeout=10.0)
    f_b = FanoutServer(stall_timeout=10.0)
    # connections 1+3 -> group a (source, then subscriber); 2+4 -> b
    group_of = lambda n, peer: "a" if n in (1, 3) else "b"  # noqa: E731
    loop = EdgeLoop(hub, fanouts={"a": f_a, "b": f_b},
                    group_of=group_of, max_sessions=4)
    try:
        port, t = _start_loop(loop)
        addr = ("127.0.0.1", port)
        src_a = socket.create_connection(addr, timeout=10)   # n=1
        src_b = socket.create_connection(addr, timeout=10)   # n=2
        time.sleep(0.2)  # both claims land before the subscribers dial
        sub_a = socket.create_connection(addr, timeout=10)   # n=3
        sub_b = socket.create_connection(addr, timeout=10)   # n=4
        src_a.sendall(SESSION_1)
        src_a.shutdown(socket.SHUT_WR)
        src_b.sendall(SESSION_4)
        src_b.shutdown(socket.SHUT_WR)
        reply_a = _decode_reply(_recv_all(src_a))
        reply_b = _decode_reply(_recv_all(src_b))
        src_a.close()
        src_b.close()
        got_a = _recv_all(sub_a)
        got_b = _recv_all(sub_b)
        sub_a.close()
        sub_b.close()
        t.join(timeout=10)
        assert got_a == SESSION_1 and got_b == SESSION_4
        assert {ch.key for ch in reply_a} == {"change-0"}
        assert {ch.key for ch in reply_b} == {"blob-0", "change-0"}
    finally:
        f_a.close()
        f_b.close()
        hub.close()


def test_edge_reconcile_leg_exchanges_exact_diff(tmp_path):
    """The --reconcile responder through the loop: the initiator's
    record shape and O(diff) exchange, identical to the threaded leg."""
    from dat_replication_protocol_tpu import sidecar
    from dat_replication_protocol_tpu.runtime import replay
    from dat_replication_protocol_tpu.runtime.reconcile_driver import (
        RatelessReplica,
        run_initiator,
    )

    def log_bytes(keys):
        return replay.encode_change_log(
            [{"key": k, "change": i, "from": i, "to": i + 1,
              "value": b"v:" + k.encode()} for i, k in enumerate(keys)])

    keys = [f"key-{i:05d}" for i in range(200)]
    logfile = tmp_path / "srv_log.bin"
    logfile.write_bytes(log_bytes(keys + ["srv-only-1", "srv-only-2"]))
    client = RatelessReplica(log_bytes(keys + ["cli-only"]))
    replica = sidecar.load_reconcile_replica(str(logfile))
    loop = EdgeLoop(reconcile_replica=replica, max_sessions=2)
    try:
        port, t = _start_loop(loop)
        for _ in range(2):  # a second session against the same replica
            c = socket.create_connection(("127.0.0.1", port), timeout=10)
            out = run_initiator(
                client, c.recv, c.sendall,
                close_write=lambda c=c: c.shutdown(socket.SHUT_WR))
            c.close()
            assert out["ok"]
            assert out["records_sent"] == 1
            assert {ch.key for ch in out["received"]} == {"srv-only-1",
                                                          "srv-only-2"}
        t.join(timeout=10)
    finally:
        pass


def test_edge_mixed_modes_share_one_session_table(tmp_path):
    """Hub sessions and reconcile responders through the SAME loop and
    the SAME table at the same time — the whole point of the rewrite."""
    from dat_replication_protocol_tpu import sidecar
    from dat_replication_protocol_tpu.runtime import replay
    from dat_replication_protocol_tpu.runtime.reconcile_driver import (
        RatelessReplica,
        run_initiator,
    )

    logfile = tmp_path / "log.bin"
    logfile.write_bytes(replay.encode_change_log(
        [{"key": "srv-only", "change": 0, "from": 0, "to": 1,
          "value": b"v"}]))
    replica = sidecar.load_reconcile_replica(str(logfile))
    client = RatelessReplica([])
    hub = ReplicationHub(linger_s=0.002)
    mode_of = lambda n, peer: "hub" if n == 1 else "reconcile"  # noqa: E731
    loop = EdgeLoop(hub, reconcile_replica=replica, mode_of=mode_of,
                    max_sessions=2)
    box = {}
    try:
        port, t = _start_loop(loop)
        addr = ("127.0.0.1", port)
        hub_c = socket.create_connection(addr, timeout=10)  # n=1: hub
        half = len(SESSION_4) // 2
        hub_c.sendall(SESSION_4[:half])  # park the hub session mid-wire

        def reconcile_leg():
            c = socket.create_connection(addr, timeout=10)  # n=2
            box["out"] = run_initiator(
                client, c.recv, c.sendall,
                close_write=lambda: c.shutdown(socket.SHUT_WR))
            c.close()

        tr = threading.Thread(target=reconcile_leg, daemon=True)
        tr.start()
        tr.join(15)
        assert not tr.is_alive(), "reconcile starved by the hub session"
        assert box["out"]["ok"]
        assert {ch.key for ch in box["out"]["received"]} == {"srv-only"}
        hub_c.sendall(SESSION_4[half:])  # now finish the hub session
        hub_c.shutdown(socket.SHUT_WR)
        reply = _decode_reply(_recv_all(hub_c))
        hub_c.close()
        t.join(timeout=10)
        assert {ch.key for ch in reply} == {"blob-0", "change-0"}
    finally:
        hub.close()


def test_edge_qos_presets_map_onto_hub_weights():
    """The QoS tiers are the existing window/weight presets, not a new
    scheduler: latency outweighs throughput, and its recv slab is the
    small one."""
    assert QOS_PRESETS["latency"]["weight"] > \
        QOS_PRESETS["throughput"]["weight"]
    assert QOS_PRESETS["latency"]["recv_cap"] < \
        QOS_PRESETS["throughput"]["recv_cap"]


def test_serve_edge_ready_cb_and_close():
    """The serve_edge entry point: ready_cb(port) fires once bound, and
    close() from another thread exits the loop promptly."""
    hub = ReplicationHub(linger_s=0.002)
    ready = threading.Event()
    box = {}
    loop = EdgeLoop(hub, tick=0.02)
    loop.bind("127.0.0.1", 0)
    t = threading.Thread(
        target=loop.serve,
        kwargs=dict(ready_cb=lambda p: (box.__setitem__("p", p),
                                        ready.set())),
        daemon=True)
    t.start()
    try:
        assert ready.wait(10)
        assert box["p"] == loop.port
        loop.close()
        t.join(10)
        assert not t.is_alive(), "close() did not stop the loop"
    finally:
        hub.close()


def test_edge_stats_fd_snapshot_carries_edge_aggregate(obs_enabled):
    """The fleet-plane satellite: snapshot_stats() (what --stats-fd and
    /snapshot serve) carries the session-table aggregate while an edge
    loop is active, and /healthz's admission stage is the edge's."""
    from dat_replication_protocol_tpu import sidecar
    from dat_replication_protocol_tpu.obs.http import default_healthz

    hub = ReplicationHub(linger_s=0.002)
    loop = EdgeLoop(hub)
    sidecar.set_active_edge(loop)
    sidecar.set_active_hub(hub)
    try:
        snap = sidecar.snapshot_stats()
        assert snap["edge"]["sessions"] == 0
        assert snap["edge"]["by_class"] == {}
        assert "pump_route" in snap["edge"]
        hz = default_healthz(sidecar._active_admission_fn())
        adm = hz["stages"]["admission"]
        assert adm["stage"] == "edge" and adm["ok"] is True
    finally:
        sidecar.set_active_hub(None)
        sidecar.set_active_edge(None)
        hub.close()
