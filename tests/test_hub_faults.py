"""Chaos isolation proof (ISSUE 8 acceptance): one misbehaving session
cannot hurt its co-residents on the shared hub.

The sweep runs >= 8 concurrent sessions per seed on ONE ReplicationHub;
exactly one session — :meth:`FaultPlan.faulty_session` — runs the
seed's stall / truncate / flip plan (the per-session scenario axis of
``FaultPlan.for_sweep``), the rest run benign plans.  The contract:

* every healthy session completes with BYTE-EXACT digests (values
  pinned against an unfaulted reference run of the same wire);
* the faulted session is shed, resumed (truncate reconnects via the
  resume layer), or torn down with ONE structured error — never a hang;
* the oracle cross-checks hub/per-session telemetry against the
  injector's ground-truth ``fault.*`` events: the predicted scenario
  actually fired, any ``hub.shed`` names only the faulty session, and
  per-session stats show the healthy sessions clean.

Tier-1 sweeps seeds 0..19 (the acceptance shape); the ``slow`` soak
covers 100 more.
"""

from __future__ import annotations

import threading
import time

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.hub import ReplicationHub, SessionShed
from dat_replication_protocol_tpu.session.faults import (
    FaultPlan,
    FaultyReader,
    bytes_reader,
)
from dat_replication_protocol_tpu.session.reconnect import (
    BackoffPolicy,
    run_resumable,
)
from dat_replication_protocol_tpu.wire.framing import ProtocolError

N_SESSIONS = 8
HARD_TIMEOUT = 25.0


def _build_wire(i: int) -> bytes:
    """One small per-session wire, distinct per index so cross-session
    routing errors surface as digest mismatches: a bulk change run (the
    native-indexed path), a KiB-scale blob (mid-blob fault territory),
    a parked change, and a tail."""
    e = protocol.encode()
    for j in range(24):
        e.change({"key": f"s{i}-b{j}", "change": j, "from": j, "to": j + 1,
                  "value": b"v%02d-%03d" % (i, j)})
    big = e.blob(1100)
    big.write(bytes([(i * 7 + k) % 251 for k in range(600)]))
    e.change({"key": f"s{i}-parked", "change": 99, "from": 0, "to": 1,
              "value": b"after-blob-%d" % i})
    big.end(bytes([(i * 13 + k) % 241 for k in range(500)]))
    for j in range(6):
        e.change({"key": f"s{i}-t{j}", "change": j, "from": j, "to": j + 1})
    e.finalize()
    out = []
    while True:
        d = e.read(4096)
        if d is None:
            break
        out.append(d)
    return b"".join(out)


_WIRES = [_build_wire(i) for i in range(N_SESSIONS)]


def _reference_digests(i: int) -> list:
    dec = protocol.decode(backend="tpu")
    digs: list = []
    dec.on_digest(lambda kind, seq, d: digs.append((kind, seq, d)))
    dec.blob(lambda b, done: b.collect(lambda _data: done()))
    for off in range(0, len(_WIRES[i]), 777):
        dec.write(_WIRES[i][off:off + 777])
    dec.end()
    assert dec.finished
    return digs


_EXPECTED = [_reference_digests(i) for i in range(N_SESSIONS)]


def _fresh_hub_decoder(hub_session):
    dec = protocol.decode(backend="tpu", pipeline=hub_session)
    digs: list = []
    dec.on_digest(lambda kind, seq, d: digs.append((kind, seq, d)))
    dec.blob(lambda b, done: b.collect(lambda _data: done()))
    return dec, digs


def _run_hub_seed(seed: int, hub: ReplicationHub):
    """All N sessions for one seed; returns {i: (outcome, payload)} with
    outcome in done/error/shed and the faulty index."""
    faulty = FaultPlan.faulty_session(seed, N_SESSIONS)
    results: dict = {}
    stats: dict = {}

    def healthy_run(i: int) -> None:
        wire = _WIRES[i]
        s = hub.register(f"seed{seed}-s{i}")
        try:
            dec, digs = _fresh_hub_decoder(s)
            plan = FaultPlan.for_sweep(seed, len(wire), attempt=0,
                                       session=i, n_sessions=N_SESSIONS)
            reader = FaultyReader(bytes_reader(wire), plan)
            while True:
                data = reader.read(1024)
                if not data:
                    break
                dec.write(data)
            dec.end()
            assert dec.finished, f"healthy session {i} did not finish"
            stats[i] = s.stats()
            results[i] = ("done", digs)
        finally:
            s.close()

    def faulty_run(i: int) -> None:
        wire = _WIRES[i]
        s = hub.register(f"seed{seed}-s{i}")
        try:
            dec, digs = _fresh_hub_decoder(s)

            def source(ckpt, failures):
                remaining = len(wire) - ckpt.wire_offset
                plan = FaultPlan.for_sweep(seed, remaining,
                                           attempt=failures, session=i,
                                           n_sessions=N_SESSIONS)
                return FaultyReader(
                    bytes_reader(wire[ckpt.wire_offset:]), plan)

            try:
                run_resumable(
                    source, dec,
                    BackoffPolicy(base=0.0005, cap=0.005, max_retries=8,
                                  seed=seed),
                    chunk_size=512, expected_total=len(wire),
                    stall_timeout=HARD_TIMEOUT / 2)
            except ProtocolError as e:
                assert e.offset is not None, f"unstructured error: {e}"
                results[i] = ("error", e)
                return
            except SessionShed as e:
                results[i] = ("shed", e)
                return
            stats[i] = s.stats()
            results[i] = ("done", digs)
        finally:
            s.close()

    threads = []
    for i in range(N_SESSIONS):
        fn = faulty_run if i == faulty else healthy_run
        threads.append(threading.Thread(target=fn, args=(i,), daemon=True))
    for t in threads:
        t.start()
    for t in threads:
        t.join(HARD_TIMEOUT)
    assert all(not t.is_alive() for t in threads), \
        f"HANG: seed {seed} sessions still running after {HARD_TIMEOUT}s"
    return results, stats, faulty


@pytest.mark.parametrize("seed", range(20))
def test_sweep_one_faulty_session_cannot_hurt_neighbors(seed, obs_enabled):
    """The acceptance sweep: 8 concurrent sessions, one faulted, with
    the telemetry oracle cross-checked against injector ground truth."""
    from dat_replication_protocol_tpu.obs.events import EVENTS

    hub = ReplicationHub(linger_s=0.002)
    try:
        results, stats, faulty = _run_hub_seed(seed, hub)
    finally:
        hub.close()

    # every healthy co-resident: completed, byte-exact digest stream
    for i in range(N_SESSIONS):
        if i == faulty:
            continue
        outcome, digs = results[i]
        assert outcome == "done", f"healthy session {i}: {results[i]}"
        assert digs == _EXPECTED[i], f"healthy session {i} digests diverged"
        assert stats[i]["shed"] is None
        assert stats[i]["delivered"] == len(_EXPECTED[i])

    # the faulted session: shed, resumed-to-completion, or ONE
    # structured error — never a hang (the join above IS that check)
    outcome, payload = results[faulty]
    assert outcome in ("done", "error", "shed"), results[faulty]
    scenario = FaultPlan.session_scenario(seed, N_SESSIONS)
    if outcome == "done" and scenario != "flip":
        # stall absorbs in place, truncate resumes: byte-exact either way
        assert payload == _EXPECTED[faulty]

    # oracle: the injector's ground-truth events say the predicted
    # scenario actually fired (fault.* events are emitted by the
    # injector itself, not the session layer under test)
    fault_events = {
        "stall": EVENTS.events("fault.stall"),
        "truncate": EVENTS.events("fault.truncate"),
        "flip": EVENTS.events("fault.flip"),
    }
    assert fault_events[scenario], \
        f"predicted scenario {scenario!r} never fired (seed {seed})"
    # ... and any shed names ONLY the faulty session
    for ev in EVENTS.events("hub.shed"):
        assert ev["fields"]["key"] == f"seed{seed}-s{faulty}"


@pytest.mark.slow
def test_sweep_soak_100_seeds():
    for seed in range(20, 120):
        hub = ReplicationHub(linger_s=0.002)
        try:
            results, stats, faulty = _run_hub_seed(seed, hub)
        finally:
            hub.close()
        for i in range(N_SESSIONS):
            if i == faulty:
                continue
            outcome, digs = results[i]
            assert outcome == "done", f"seed {seed} session {i} {outcome}"
            assert digs == _EXPECTED[i], f"seed {seed} session {i} diverged"


# -- targeted isolation arms --------------------------------------------------


def test_long_stall_does_not_stall_neighbors():
    """A session stalled for seconds mid-wire: the 7 healthy sessions
    must finish long before the stall ends — the cross-session-stall
    exclusion measured, not assumed."""
    hub = ReplicationHub(linger_s=0.002)
    done_at: dict = {}
    t0 = time.monotonic()

    def healthy_run(i: int) -> None:
        s = hub.register(f"h{i}")
        try:
            dec, digs = _fresh_hub_decoder(s)
            for off in range(0, len(_WIRES[i]), 777):
                dec.write(_WIRES[i][off:off + 777])
            dec.end()
            assert dec.finished and digs == _EXPECTED[i]
            done_at[i] = time.monotonic() - t0
        finally:
            s.close()

    def stalled_run() -> None:
        s = hub.register("staller")
        try:
            dec, digs = _fresh_hub_decoder(s)
            plan = FaultPlan(seed=1, stall_at=len(_WIRES[0]) // 2,
                             stall_s=3.0)
            reader = FaultyReader(bytes_reader(_WIRES[0]), plan)
            while True:
                data = reader.read(512)
                if not data:
                    break
                dec.write(data)
            dec.end()
            assert dec.finished and digs == _EXPECTED[0]
            done_at["staller"] = time.monotonic() - t0
        finally:
            s.close()

    threads = [threading.Thread(target=stalled_run, daemon=True)]
    threads += [threading.Thread(target=healthy_run, args=(i,), daemon=True)
                for i in range(1, N_SESSIONS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(HARD_TIMEOUT)
    assert all(not t.is_alive() for t in threads), "HANG"
    hub.close()
    healthy_times = [done_at[i] for i in range(1, N_SESSIONS)]
    assert max(healthy_times) < 2.5, \
        f"neighbors waited on the stalled session: {healthy_times}"
    assert done_at["staller"] >= 3.0  # it really did stall


def test_mid_blob_truncation_resumes_while_neighbors_run():
    """Truncation INSIDE the faulty session's blob payload: the resume
    layer reconnects it to a byte-exact finish; co-residents sharing
    the engine stay byte-exact throughout."""
    hub = ReplicationHub(linger_s=0.002)
    results: dict = {}

    def healthy_run(i: int) -> None:
        s = hub.register(f"h{i}")
        try:
            dec, digs = _fresh_hub_decoder(s)
            for off in range(0, len(_WIRES[i]), 513):
                dec.write(_WIRES[i][off:off + 513])
            dec.end()
            results[i] = (dec.finished, digs)
        finally:
            s.close()

    def truncated_run() -> None:
        wire = _WIRES[0]
        s = hub.register("trunc")
        try:
            dec, digs = _fresh_hub_decoder(s)
            cut = int(len(wire) * 0.55)  # inside the 1.1 KiB blob

            def source(ckpt, failures):
                plan = FaultPlan(seed=3,
                                 truncate_at=(cut - ckpt.wire_offset)
                                 if failures == 0 else None)
                return FaultyReader(
                    bytes_reader(wire[ckpt.wire_offset:]), plan)

            stats = run_resumable(
                source, dec,
                BackoffPolicy(base=0.0001, max_retries=2, seed=0),
                expected_total=len(wire), stall_timeout=5)
            results["trunc"] = (stats["reconnects"], digs)
        finally:
            s.close()

    threads = [threading.Thread(target=truncated_run, daemon=True)]
    threads += [threading.Thread(target=healthy_run, args=(i,), daemon=True)
                for i in range(1, N_SESSIONS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(HARD_TIMEOUT)
    assert all(not t.is_alive() for t in threads), "HANG"
    hub.close()
    reconnects, digs = results["trunc"]
    assert reconnects == 1
    assert digs == _EXPECTED[0]  # exactly-once digests across the resume
    for i in range(1, N_SESSIONS):
        finished, digs = results[i]
        assert finished and digs == _EXPECTED[i]


def test_byzantine_garbage_session_torn_down_alone(obs_enabled):
    """A session speaking garbage (hostile length varint) dies with ONE
    structured ProtocolError and releases its hub slot; co-residents
    complete byte-exact and the hub admits a replacement."""
    hub = ReplicationHub(max_sessions=N_SESSIONS, linger_s=0.002)
    results: dict = {}

    def healthy_run(i: int) -> None:
        s = hub.register(f"h{i}")
        try:
            dec, digs = _fresh_hub_decoder(s)
            for off in range(0, len(_WIRES[i]), 777):
                dec.write(_WIRES[i][off:off + 777])
            dec.end()
            results[i] = (dec.finished, digs)
        finally:
            s.close()

    def byzantine_run() -> None:
        from dat_replication_protocol_tpu.session.decoder import (
            DecoderDestroyedError,
        )

        s = hub.register("byz")
        try:
            dec, _digs = _fresh_hub_decoder(s)
            errs: list = []
            dec.on_error(errs.append)
            try:
                dec.write(b"\xff" * 64)
                dec.end()
            except (ProtocolError, DecoderDestroyedError):
                pass  # the destroy cascade may surface either way
            if errs and isinstance(errs[0], ProtocolError):
                results["byz"] = ("error", errs[0])
            else:
                results["byz"] = ("no-error", errs)
        finally:
            s.close()

    threads = [threading.Thread(target=byzantine_run, daemon=True)]
    threads += [threading.Thread(target=healthy_run, args=(i,), daemon=True)
                for i in range(1, N_SESSIONS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(HARD_TIMEOUT)
    assert all(not t.is_alive() for t in threads), "HANG"
    outcome, err = results["byz"]
    assert outcome == "error" and err.offset is not None
    for i in range(1, N_SESSIONS):
        finished, digs = results[i]
        assert finished and digs == _EXPECTED[i]
    # the slot was released: a full-capacity hub admits a replacement
    replacement = hub.register("fresh")
    replacement.close()
    hub.close()
