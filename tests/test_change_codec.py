import pytest

from dat_replication_protocol_tpu.wire.change_codec import (
    Change,
    decode_change,
    encode_change,
)


def test_roundtrip_basic():
    c = Change(key="key", change=1, from_=0, to=1, value=b"hello")
    out = decode_change(encode_change(c))
    # decoded optionals default to '' / b'' — matches the reference suite's
    # expectation of `subset: ''` (reference: test/basic.js:10-17)
    assert out == Change(key="key", change=1, from_=0, to=1, value=b"hello", subset="")


def test_roundtrip_dict_with_from_keyword():
    d = {"key": "some-row", "change": 7, "from": 3, "to": 4, "value": b"v", "subset": "s"}
    out = decode_change(encode_change(d))
    assert out.to_dict() == {
        "subset": "s",
        "key": "some-row",
        "change": 7,
        "from": 3,
        "to": 4,
        "value": b"v",
    }


def test_golden_bytes_no_optionals():
    # Hand-computed proto2 encoding: key(2)="key", change(3)=1, from(4)=0, to(5)=1
    c = Change(key="key", change=1, from_=0, to=1)
    assert encode_change(c) == b"\x12\x03key\x18\x01\x20\x00\x28\x01"


def test_golden_bytes_all_fields():
    c = Change(key="k", change=300, from_=1, to=2, value=b"\x00\xff", subset="s")
    assert (
        encode_change(c)
        == b"\x0a\x01s" + b"\x12\x01k" + b"\x18\xac\x02" + b"\x20\x01" + b"\x28\x02" + b"\x32\x02\x00\xff"
    )


def test_matches_google_protobuf_if_available():
    """Cross-check byte-compatibility against the canonical protobuf runtime."""
    pytest.importorskip("google.protobuf")
    from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

    pool = descriptor_pool.DescriptorPool()
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = "change_xcheck.proto"
    fdp.syntax = "proto2"
    msg = fdp.message_type.add()
    msg.name = "Change"
    fields = [
        ("subset", 1, "TYPE_STRING", "LABEL_OPTIONAL"),
        ("key", 2, "TYPE_STRING", "LABEL_REQUIRED"),
        ("change", 3, "TYPE_UINT32", "LABEL_REQUIRED"),
        ("from", 4, "TYPE_UINT32", "LABEL_REQUIRED"),
        ("to", 5, "TYPE_UINT32", "LABEL_REQUIRED"),
        ("value", 6, "TYPE_BYTES", "LABEL_OPTIONAL"),
    ]
    for name, num, ftype, label in fields:
        f = msg.field.add()
        f.name = name
        f.number = num
        f.type = getattr(descriptor_pb2.FieldDescriptorProto, ftype)
        f.label = getattr(descriptor_pb2.FieldDescriptorProto, label)
    pool.Add(fdp)
    cls = message_factory.GetMessageClass(pool.FindMessageTypeByName("Change"))

    m = cls()
    m.key = "row-1"
    m.change = 9
    setattr(m, "from", 123456)
    m.to = 123457
    m.value = b"payload \x00 bytes"
    m.subset = "sub"
    golden = m.SerializeToString()

    ours = encode_change(
        Change(key="row-1", change=9, from_=123456, to=123457, value=b"payload \x00 bytes", subset="sub")
    )
    assert ours == golden

    out = decode_change(golden)
    assert out.key == "row-1" and out.from_ == 123456 and out.to == 123457


def test_unknown_fields_skipped():
    base = encode_change(Change(key="k", change=1, from_=0, to=1))
    # append unknown field 7 (varint) and field 8 (fixed32)
    extra = b"\x38\x2a" + b"\x45\x01\x02\x03\x04"
    out = decode_change(base + extra)
    assert out.key == "k"


def test_missing_required_rejected():
    with pytest.raises(ValueError):
        decode_change(b"\x18\x01")  # only change=1


def test_uint32_range_enforced():
    with pytest.raises(ValueError):
        encode_change(Change(key="k", change=2**32, from_=0, to=1))
    with pytest.raises(ValueError):
        encode_change(Change(key="k", change=-1, from_=0, to=1))


def test_utf8_and_binary_values():
    c = Change(key="ключ-🔑", change=1, from_=0, to=1, value=bytes(range(256)), subset="αβ")
    out = decode_change(encode_change(c))
    assert out.key == "ключ-🔑" and out.value == bytes(range(256)) and out.subset == "αβ"


def test_c_encoder_byte_identical_fuzz():
    """dat_fastpath.encode_change_c must be byte-identical to the Python
    encoder across randomized field shapes (incl. varint width edges,
    absent/empty optionals, non-ASCII strings)."""
    import random

    from dat_replication_protocol_tpu.runtime import fastpath

    fp = fastpath.get()
    if fp is None:
        import pytest
        pytest.skip("dat_fastpath unavailable")
    rng = random.Random(7)
    edge_ints = [0, 1, 127, 128, 16383, 16384, (1 << 21) - 1, 1 << 21,
                 (1 << 28) - 1, 1 << 28, 0xFFFFFFFF]
    for i in range(500):
        key = "".join(rng.choice("abÅ→€z0") for _ in range(rng.randrange(0, 40)))
        subset = rng.choice([None, "", "s", "ünïcode·" * rng.randrange(1, 4)])
        value = rng.choice([None, b"", bytes(rng.randrange(0, 200))])
        cg = rng.choice(edge_ints)
        fr = rng.choice(edge_ints)
        to = rng.choice(edge_ints)
        ch = Change(key=key, change=cg, from_=fr, to=to, value=value,
                    subset=subset)
        got_c = fp.encode_change_c(key, cg, fr, to, value, subset)
        from dat_replication_protocol_tpu.wire.change_codec import (
            _encode_change_py,
        )
        want = _encode_change_py(ch)
        assert got_c == want, (i, key, subset, value, cg, fr, to)
        # and both decode back to the same record
        assert decode_change(got_c) == decode_change(want)


def test_c_encoder_validation_parity():
    from dat_replication_protocol_tpu.runtime import fastpath

    fp = fastpath.get()
    if fp is None:
        import pytest
        pytest.skip("dat_fastpath unavailable")
    import pytest
    with pytest.raises(ValueError, match="uint32"):
        fp.encode_change_c("k", -1, 0, 1, None, None)
    with pytest.raises(ValueError, match="uint32"):
        fp.encode_change_c("k", 1 << 32, 0, 1, None, None)
    with pytest.raises(ValueError, match="key is required"):
        fp.encode_change_c(None, 1, 0, 1, None, None)


def test_c_decoder_differential_fuzz():
    """decode_change_c vs the Python parser on (a) valid encoded records
    round-tripped, (b) mutated/truncated payloads, (c) pure random
    bytes: identical records on success, same error CLASS (ValueError)
    on failure — the C parser must never accept what Python rejects or
    vice versa."""
    import random

    from dat_replication_protocol_tpu.runtime import fastpath
    from dat_replication_protocol_tpu.wire.change_codec import (
        _decode_change_py,
    )

    fp = fastpath.get()
    if fp is None:
        import pytest
        pytest.skip("dat_fastpath unavailable")
    rng = random.Random(11)

    def compare(payload, ctx):
        try:
            want = _decode_change_py(payload)
            want_err = None
        except ValueError as e:
            want, want_err = None, e
        try:
            got = fp.decode_change_c(Change, payload)
            got_err = None
        except ValueError as e:
            got, got_err = None, e
        if want_err is not None:
            assert got_err is not None, (ctx, payload, got)
        else:
            assert got_err is None, (ctx, payload, want_err, got_err)
            assert got == want, (ctx, payload)

    edge_ints = [0, 1, 127, 128, 16383, 16384, (1 << 28) - 1, 1 << 28,
                 0xFFFFFFFF]
    for i in range(400):
        ch = Change(
            key="".join(rng.choice("abÅ€z") for _ in range(rng.randrange(0, 20))),
            change=rng.choice(edge_ints),
            from_=rng.choice(edge_ints),
            to=rng.choice(edge_ints),
            value=rng.choice([None, b"", bytes(rng.randrange(0, 60))]),
            subset=rng.choice([None, "", "sü" * rng.randrange(1, 3)]),
        )
        wire = encode_change(ch)
        compare(wire, ("roundtrip", i))
        # truncations
        if len(wire) > 1:
            compare(wire[: rng.randrange(1, len(wire))], ("trunc", i))
        # single-byte mutation
        mut = bytearray(wire)
        mut[rng.randrange(len(mut))] ^= 1 << rng.randrange(8)
        compare(bytes(mut), ("mutate", i))
        # garbage
        compare(bytes(rng.randrange(256) for _ in range(rng.randrange(0, 40))),
                ("garbage", i))
        # >32-bit varints truncate identically (foreign encoders)
        big = (1 << 34) | rng.choice(edge_ints)
        from dat_replication_protocol_tpu.wire.varint import encode_uvarint
        payload = (bytes([0x12, 0x01]) + b"k" + bytes([0x18])
                   + encode_uvarint(big)
                   + bytes([0x20, 0x00, 0x28, 0x01]))
        compare(payload, ("u64-trunc", i))


def test_exotic_buffer_values_keep_parity():
    """Strided / multi-itemsize memoryviews must produce identical,
    SELF-CONSISTENT wire on both paths (the length prefix must count
    the serialized bytes — a 4-byte-itemsize view's len() is elements,
    not bytes), and strided views must decode on both paths."""
    import array

    from dat_replication_protocol_tpu.wire.change_codec import (
        _decode_change_py,
        _encode_change_py,
    )

    strided = memoryview(b"abcdef")[::2]
    multi = memoryview(array.array("I", [1, 2]))
    for value in (strided, multi, memoryview(b"plain"), bytearray(b"ba")):
        ch = Change(key="k", change=1, from_=0, to=1, value=value)
        wire = encode_change(ch)
        assert wire == _encode_change_py(ch)
        back = decode_change(wire)
        assert back.value == bytes(value)
        assert back == _decode_change_py(wire)
    # a strided view OF a payload decodes via the Python fallback
    payload = encode_change(Change(key="kk", change=7, from_=0, to=1))
    doubled = bytes(b for byte in payload for b in (byte, 0))
    assert decode_change(memoryview(doubled)[::2]) == decode_change(payload)


def test_decode_exotic_buffers_keep_python_semantics():
    """Strided numpy arrays and multi-itemsize views must decode with
    the Python parser's semantics regardless of whether the C extension
    compiled (round-5 review: exception-sniffing mistook numpy's
    non-contiguous ValueError for a corrupt payload)."""
    import array

    import numpy as np

    from dat_replication_protocol_tpu.wire.change_codec import (
        _decode_change_py,
    )

    payload = encode_change(Change(key="kk", change=7, from_=0, to=1,
                                   value=b"xy"))
    # strided ndarray view of a doubled payload
    doubled = np.frombuffer(
        bytes(b for byte in payload for b in (byte, 0)), dtype=np.uint8)
    assert decode_change(doubled[::2]) == _decode_change_py(doubled[::2])
    # contiguous ndarray still decodes
    arr = np.frombuffer(payload, dtype=np.uint8)
    assert decode_change(arr) == _decode_change_py(payload)
    # multi-itemsize memoryview: per-element semantics preserved
    a = array.array("I", [0x12, 1, ord("k"), 0x18, 1, 0x20, 0, 0x28, 1])
    mv = memoryview(a)
    assert decode_change(mv) == _decode_change_py(mv)


def test_fastpath_gate_is_shared_and_flips_with_env(monkeypatch):
    """The codec and the decoder's dispatch loop route through ONE
    fast-path gate (runtime.fastpath.get) with one caching policy: the
    DISABLE env var is re-read per call, so flipping it mid-process
    switches BOTH layers together (round-5 advisor: the codec's private
    cache froze the decision while the decoder re-read it — tests that
    "forced the pure-Python path" were exercising half of it)."""
    from dat_replication_protocol_tpu.runtime import fastpath
    from dat_replication_protocol_tpu.session import decoder as session_decoder
    from dat_replication_protocol_tpu.wire import change_codec

    monkeypatch.delenv("DAT_FASTPATH_DISABLE", raising=False)
    before = fastpath.get()  # may be None on a toolchain-less image
    assert change_codec._fastpath_mod() is before
    assert session_decoder._fastpath_mod() is before

    # flip mid-process, AFTER first use: both layers must see it now
    monkeypatch.setenv("DAT_FASTPATH_DISABLE", "1")
    assert change_codec._fastpath_mod() is None
    assert session_decoder._fastpath_mod() is None

    # and flip back: a call made while disabled must not have poisoned
    # the import cache
    monkeypatch.delenv("DAT_FASTPATH_DISABLE")
    assert change_codec._fastpath_mod() is before
    assert session_decoder._fastpath_mod() is before


def test_fastpath_reset_hook_drops_cached_import(monkeypatch):
    """reset_for_tests() re-arms the one-shot build+import decision so a
    test can exercise a clean first call (the disk build cache makes the
    rebuild cheap)."""
    from dat_replication_protocol_tpu.runtime import fastpath

    monkeypatch.delenv("DAT_FASTPATH_DISABLE", raising=False)
    before = fastpath.get()
    fastpath.reset_for_tests()
    assert fastpath._tried is False and fastpath._mod is None
    again = fastpath.get()
    assert (again is None) == (before is None)
    if before is not None:  # a fresh module object, same extension
        assert again.__name__ == before.__name__
    fastpath.reset_for_tests()  # leave no cross-test state behind
    fastpath.get()
