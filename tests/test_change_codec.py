import pytest

from dat_replication_protocol_tpu.wire.change_codec import (
    Change,
    decode_change,
    encode_change,
)


def test_roundtrip_basic():
    c = Change(key="key", change=1, from_=0, to=1, value=b"hello")
    out = decode_change(encode_change(c))
    # decoded optionals default to '' / b'' — matches the reference suite's
    # expectation of `subset: ''` (reference: test/basic.js:10-17)
    assert out == Change(key="key", change=1, from_=0, to=1, value=b"hello", subset="")


def test_roundtrip_dict_with_from_keyword():
    d = {"key": "some-row", "change": 7, "from": 3, "to": 4, "value": b"v", "subset": "s"}
    out = decode_change(encode_change(d))
    assert out.to_dict() == {
        "subset": "s",
        "key": "some-row",
        "change": 7,
        "from": 3,
        "to": 4,
        "value": b"v",
    }


def test_golden_bytes_no_optionals():
    # Hand-computed proto2 encoding: key(2)="key", change(3)=1, from(4)=0, to(5)=1
    c = Change(key="key", change=1, from_=0, to=1)
    assert encode_change(c) == b"\x12\x03key\x18\x01\x20\x00\x28\x01"


def test_golden_bytes_all_fields():
    c = Change(key="k", change=300, from_=1, to=2, value=b"\x00\xff", subset="s")
    assert (
        encode_change(c)
        == b"\x0a\x01s" + b"\x12\x01k" + b"\x18\xac\x02" + b"\x20\x01" + b"\x28\x02" + b"\x32\x02\x00\xff"
    )


def test_matches_google_protobuf_if_available():
    """Cross-check byte-compatibility against the canonical protobuf runtime."""
    pytest.importorskip("google.protobuf")
    from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

    pool = descriptor_pool.DescriptorPool()
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = "change_xcheck.proto"
    fdp.syntax = "proto2"
    msg = fdp.message_type.add()
    msg.name = "Change"
    fields = [
        ("subset", 1, "TYPE_STRING", "LABEL_OPTIONAL"),
        ("key", 2, "TYPE_STRING", "LABEL_REQUIRED"),
        ("change", 3, "TYPE_UINT32", "LABEL_REQUIRED"),
        ("from", 4, "TYPE_UINT32", "LABEL_REQUIRED"),
        ("to", 5, "TYPE_UINT32", "LABEL_REQUIRED"),
        ("value", 6, "TYPE_BYTES", "LABEL_OPTIONAL"),
    ]
    for name, num, ftype, label in fields:
        f = msg.field.add()
        f.name = name
        f.number = num
        f.type = getattr(descriptor_pb2.FieldDescriptorProto, ftype)
        f.label = getattr(descriptor_pb2.FieldDescriptorProto, label)
    pool.Add(fdp)
    cls = message_factory.GetMessageClass(pool.FindMessageTypeByName("Change"))

    m = cls()
    m.key = "row-1"
    m.change = 9
    setattr(m, "from", 123456)
    m.to = 123457
    m.value = b"payload \x00 bytes"
    m.subset = "sub"
    golden = m.SerializeToString()

    ours = encode_change(
        Change(key="row-1", change=9, from_=123456, to=123457, value=b"payload \x00 bytes", subset="sub")
    )
    assert ours == golden

    out = decode_change(golden)
    assert out.key == "row-1" and out.from_ == 123456 and out.to == 123457


def test_unknown_fields_skipped():
    base = encode_change(Change(key="k", change=1, from_=0, to=1))
    # append unknown field 7 (varint) and field 8 (fixed32)
    extra = b"\x38\x2a" + b"\x45\x01\x02\x03\x04"
    out = decode_change(base + extra)
    assert out.key == "k"


def test_missing_required_rejected():
    with pytest.raises(ValueError):
        decode_change(b"\x18\x01")  # only change=1


def test_uint32_range_enforced():
    with pytest.raises(ValueError):
        encode_change(Change(key="k", change=2**32, from_=0, to=1))
    with pytest.raises(ValueError):
        encode_change(Change(key="k", change=-1, from_=0, to=1))


def test_utf8_and_binary_values():
    c = Change(key="ключ-🔑", change=1, from_=0, to=1, value=bytes(range(256)), subset="αβ")
    out = decode_change(encode_change(c))
    assert out.key == "ключ-🔑" and out.value == bytes(range(256)) and out.subset == "αβ"
