"""Chaos-oracle conformance: telemetry must agree with ground truth.

The fault injector (session/faults.py) is the observability layer's
oracle (ISSUE 3): it KNOWS what it did to the wire — every drop,
truncation, stall, flip, and re-segmentation it injected — and the
reconnect driver independently counts attempts/reconnects in its stats
dict (the PR-2 machinery, tested on its own in test_session_faults.py).
This suite runs the 20-seed ``FaultPlan.for_sweep`` sweep with
telemetry enabled and asserts three-way agreement:

* every injected fault kind is reflected by a matching metric/event
  (drop/truncate -> ``reconnect.fault``; stall -> ``fault.stall`` with
  the plan's duration; reseg -> the segment counter; flip -> a
  ``protocol.error`` event, targeted test);
* reconnect attempt/backoff counts in the metrics equal the driver's
  stats AND the actual sleeps taken (captured via the policy's
  injectable sleep);
* the telemetry counters mirror the session's passive counters
  (``decoder.changes`` metric == ``dec.changes`` attribute, ...) — the
  layer measures the session, not itself.
"""

from __future__ import annotations

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.obs import events as obs_events
from dat_replication_protocol_tpu.obs import metrics as obs_metrics
from dat_replication_protocol_tpu.session.faults import (
    FaultPlan,
    FaultyReader,
    bytes_reader,
)
from dat_replication_protocol_tpu.session.reconnect import (
    BackoffPolicy,
    run_resumable,
)
from dat_replication_protocol_tpu.session.resume import WireJournal
from dat_replication_protocol_tpu.wire.framing import ProtocolError

EVENTS = obs_events.EVENTS


def _build_wire() -> bytes:
    """Same scenario coverage as the PR-2 sweep: a bulk change run, two
    interleaved corked blobs, a parked change, a multi-KiB blob, tails."""
    e = protocol.encode()
    j = WireJournal()
    e.attach_journal(j)
    for i in range(24):
        e.change({"key": f"bulk-{i}", "change": i, "from": i, "to": i + 1,
                  "value": b"v%03d" % i})
    b1 = e.blob(11)
    b2 = e.blob(11)
    b1.write(b"hello ")
    b2.write(b"HELLO ")
    b1.write(b"world")
    b2.write(b"WORLD")
    b1.end()
    b2.end()
    big = e.blob(3000)
    big.write(b"x" * 1700)
    e.change({"key": "parked", "change": 99, "from": 0, "to": 1,
              "value": b"after-blob"})
    big.end(b"y" * 1300)
    for i in range(8):
        e.change({"key": f"tail-{i}", "change": i, "from": i, "to": i + 1})
    e.finalize()
    while e.read(4096) is not None:
        pass
    return j.read_from(0)


_WIRE = _build_wire()


def _counter_value(name: str) -> int:
    return obs_metrics.REGISTRY.counter(name).value


def _plan_kind(plan: FaultPlan) -> str | None:
    if plan.drop_at is not None:
        return "drop"
    if plan.truncate_at is not None:
        return "truncate"
    if plan.stall_at is not None:
        return "stall"
    if plan.max_segment == 1:
        return "reseg"
    return None


def _run_seed_with_oracle(seed: int) -> dict:
    """One fully-instrumented seed; returns every ground-truth record
    the assertions need."""
    obs_metrics.REGISTRY.reset()
    EVENTS.clear()
    dec = protocol.decode()
    delivered: list = []
    dec.change(lambda c, done: (delivered.append(("change", c.key)), done()))
    dec.blob(lambda b, done: b.collect(
        lambda data: (delivered.append(("blob", len(data))), done())))

    journal = WireJournal()
    journal.append(_WIRE)
    plans: list[FaultPlan] = []
    source_offsets: list[int] = []

    def source(ckpt, failures):
        source_offsets.append(ckpt.wire_offset)
        replay = journal.read_from(ckpt.wire_offset)
        plan = FaultPlan.for_sweep(seed, len(replay), attempt=failures)
        plans.append(plan)
        return FaultyReader(bytes_reader(replay), plan)

    sleeps: list[float] = []  # ground truth: the sleeps actually taken

    def sleep(d: float) -> None:
        sleeps.append(d)

    stats = run_resumable(
        source, dec,
        BackoffPolicy(base=0.0005, cap=0.005, max_retries=8, seed=seed,
                      sleep=sleep),
        chunk_size=1024, expected_total=len(_WIRE), stall_timeout=15)
    return {
        "stats": stats, "dec": dec, "plans": plans,
        "source_offsets": source_offsets, "sleeps": sleeps,
        "delivered": delivered,
    }


def test_sweep_telemetry_matches_ground_truth(obs_enabled):
    kinds_seen: set[str] = set()
    for seed in range(20):
        r = _run_seed_with_oracle(seed)
        stats, dec = r["stats"], r["dec"]
        ctx = f"seed {seed}"

        # -- driver ground truth vs reconnect metrics/events ------------
        assert _counter_value("reconnect.attempts") == stats["attempts"], ctx
        assert len(EVENTS.events("session.connect")) == stats["attempts"], ctx
        assert _counter_value("reconnect.faults") == len(stats["faults"]), ctx
        assert len(EVENTS.events("reconnect.fault")) == len(stats["faults"]), ctx
        # converged sweep seeds absorb every fault: reconnects == faults
        assert _counter_value("reconnect.backoffs") == stats["reconnects"], ctx

        # -- backoff: events match the sleeps the policy actually took --
        backoffs = [e["fields"]["seconds"]
                    for e in EVENTS.events("reconnect.backoff")]
        assert len(backoffs) == stats["reconnects"], ctx
        # sleep() is skipped for d == 0 but the event always fires: every
        # nonzero recorded sleep must appear, in order, with exact values
        assert [d for d in backoffs if d > 0] == r["sleeps"], ctx

        # -- injected faults vs session-layer recovery ------------------
        inj_drops = EVENTS.events("fault.drop")
        inj_truncs = EVENTS.events("fault.truncate")
        # every disconnect-class injection produced exactly one driver
        # fault, and nothing else did
        assert len(inj_drops) + len(inj_truncs) == len(stats["faults"]), ctx
        assert len(EVENTS.events("session.truncated")) == len(inj_truncs), ctx
        for plan, off0 in zip(r["plans"], r["source_offsets"]):
            kind = _plan_kind(plan)
            if kind:
                kinds_seen.add(kind)
            if kind == "drop":
                assert any(e["fields"]["offset"] == plan.drop_at
                           for e in inj_drops), ctx
            elif kind == "truncate":
                assert any(e["fields"]["offset"] == plan.truncate_at
                           for e in inj_truncs), ctx
            elif kind == "stall":
                stall_events = EVENTS.events("fault.stall")
                assert any(e["fields"]["seconds"] == plan.stall_s
                           for e in stall_events), ctx
            elif kind == "reseg":
                assert _counter_value(
                    "fault.injected.reseg_segments") > 0, ctx

        # -- journal replay bytes == what the source really re-read -----
        expected_replay = sum(len(_WIRE) - off for off in r["source_offsets"])
        assert _counter_value("journal.replay.bytes") == expected_replay, ctx
        assert len(EVENTS.events("journal.replay")) == len(
            r["source_offsets"]), ctx

        # -- telemetry mirrors the session's passive counters -----------
        assert _counter_value("decoder.changes") == dec.changes, ctx
        assert _counter_value("decoder.blobs") == dec.blobs, ctx
        assert _counter_value("decoder.bytes") == dec.bytes, ctx
        # a clean completion emits exactly one session.complete carrying
        # the driver's own totals
        completes = EVENTS.events("session.complete")
        assert len(completes) == 1, ctx
        assert completes[0]["fields"]["reconnects"] == stats["reconnects"], ctx
        assert completes[0]["fields"]["bytes"] == dec.bytes, ctx

    # 20 seeds must exercise every disconnect-class kind the sweep
    # generator can draw (flip is corruption-class: targeted below)
    assert kinds_seen == {"drop", "truncate", "stall", "reseg"}, kinds_seen


@pytest.mark.slow
def test_sweep_soak_seeds_20_to_120(obs_enabled):
    """Soak arm (marker already registered in pyproject): 100 more
    seeds of the core agreement invariants."""
    for seed in range(20, 120):
        r = _run_seed_with_oracle(seed)
        stats = r["stats"]
        ctx = f"seed {seed}"
        assert _counter_value("reconnect.attempts") == stats["attempts"], ctx
        assert _counter_value("reconnect.faults") == len(stats["faults"]), ctx
        assert len(EVENTS.events("fault.drop")) + len(
            EVENTS.events("fault.truncate")) == len(stats["faults"]), ctx
        assert _counter_value("decoder.changes") == r["dec"].changes, ctx


def test_header_flip_surfaces_as_matching_protocol_error_event(obs_enabled):
    def source(ckpt, failures):
        plan = FaultPlan(seed=1,
                         flip_at=1 - ckpt.wire_offset
                         if ckpt.wire_offset <= 1 else None, flip_mask=0x44)
        return FaultyReader(bytes_reader(_WIRE[ckpt.wire_offset:]), plan)

    dec = protocol.decode()
    with pytest.raises(ProtocolError) as ei:
        run_resumable(source, dec,
                      BackoffPolicy(base=0, max_retries=2, seed=0),
                      expected_total=len(_WIRE), stall_timeout=5)
    # the injector recorded the flip, the decoder recorded the error,
    # and the two coordinates agree with the raised exception
    assert EVENTS.count("fault.flip") >= 1
    errors = EVENTS.events("protocol.error")
    assert len(errors) >= 1
    assert errors[-1]["fields"]["offset"] == ei.value.offset
    assert errors[-1]["fields"]["frame"] == ei.value.frame
    assert obs_metrics.REGISTRY.counter("decoder.errors").value >= 1


def test_app_stall_emits_structured_stall_event(obs_enabled):
    dec = protocol.decode()
    dec.change(lambda c, done: None)  # never acks: the app stall

    def source(ckpt, failures):
        return FaultyReader(bytes_reader(_WIRE[ckpt.wire_offset:]),
                            FaultPlan(seed=0))

    with pytest.raises(ProtocolError) as ei:
        run_resumable(source, dec, BackoffPolicy(base=0, max_retries=0),
                      expected_total=len(_WIRE),
                      stall_timeout=0.2, wait_step=0.05)
    assert "stalled" in str(ei.value)
    stalls = EVENTS.events("session.stall")
    assert len(stalls) == 1
    assert stalls[0]["fields"]["kind"] == "app-ack"
    assert stalls[0]["fields"]["offset"] == ei.value.offset


def test_sweep_seed_disabled_gate_records_nothing():
    """The whole instrumented stack behind one dark gate: a faulted,
    resumed session with obs off must leave zero telemetry."""
    obs_metrics.REGISTRY.reset()
    EVENTS.clear()
    assert not obs_metrics.OBS.on
    dec = protocol.decode()

    def source(ckpt, failures):
        plan = FaultPlan.for_sweep(3, len(_WIRE) - ckpt.wire_offset,
                                   attempt=failures)
        return FaultyReader(bytes_reader(_WIRE[ckpt.wire_offset:]), plan)

    stats = run_resumable(
        source, dec,
        BackoffPolicy(base=0.0005, cap=0.005, max_retries=8, seed=3),
        chunk_size=1024, expected_total=len(_WIRE), stall_timeout=15)
    assert stats is not None and dec.finished
    snap = obs_metrics.snapshot()
    assert all(v == 0 for v in snap["counters"].values())
    assert all(h["count"] == 0 for h in snap["histograms"].values())
    assert EVENTS.events() == []
