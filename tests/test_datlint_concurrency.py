"""Whole-program concurrency analyzer (ISSUE 13): fixture suites for
lock-order, blocking-under-lock, and guarded-state, the
wire-dispatch-parity matrix rule, the structured CLI (--json /
--baseline / --stats / --lock-graph), and one regression test per true
positive the pass found in production code.

Fixture doctrine (same as test_datlint.py): each bad fixture is a
minimal re-creation of the PRE-fix repo pattern — if a rule stops
firing on it, the analyzer has lost the bug class that motivated it.
"""

import json
import textwrap

import pytest

from dat_replication_protocol_tpu.analysis import run_paths
from dat_replication_protocol_tpu.analysis.__main__ import main as datlint_main
from dat_replication_protocol_tpu.analysis.concurrency import (
    BlockingUnderLock,
    GuardedState,
    LockOrder,
)

CONC_RULES = (LockOrder(), BlockingUnderLock(), GuardedState())


def _lint(tmp_path, *files, rules=CONC_RULES):
    for name, source in files:
        (tmp_path / name).write_text(textwrap.dedent(source))
    return run_paths([tmp_path], rules=rules)


def _rules_fired(findings):
    return {f.rule for f in findings}


# -- lock-order: inversions ---------------------------------------------------

# the classic: one thread locks a then b, another locks b then a
TWO_LOCK_INVERSION = '''
import threading

class Engine:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()

    def forward(self):
        with self._alock:
            with self._block:
                pass

    def backward(self):
        with self._block:
            with self._alock:
                pass
'''


def test_lock_order_fires_on_two_lock_inversion(tmp_path):
    findings = _lint(tmp_path, ("inv.py", TWO_LOCK_INVERSION))
    assert "lock-order" in _rules_fired(findings)
    inv = [f for f in findings if f.rule == "lock-order"]
    # the finding cites BOTH acquisition chains (one per direction)
    assert inv[0].chains and len(inv[0].chains) == 2
    assert "forward" in inv[0].message and "backward" in inv[0].message


def test_lock_order_fires_on_three_lock_cycle(tmp_path):
    findings = _lint(tmp_path, ("cycle3.py", '''
        import threading

        A = threading.Lock()
        B = threading.Lock()
        C = threading.Lock()

        def ab():
            with A:
                with B:
                    pass

        def bc():
            with B:
                with C:
                    pass

        def ca():
            with C:
                with A:
                    pass
    '''))
    inv = [f for f in findings if f.rule == "lock-order"]
    assert inv, findings
    assert len(inv[0].chains) == 3  # one chain per cycle edge


def test_lock_order_is_whole_program_across_files(tmp_path):
    # each file is single-order-clean; only the cross-file composition
    # inverts — the exact blind spot of a per-file pass.  (The import
    # cycle is fine: the analyzer reads ASTs, nothing executes.)
    findings = _lint(
        tmp_path,
        ("liblog.py", '''
            import threading
            from server import SRV

            class Log:
                def __init__(self):
                    self._lock = threading.Lock()

                def append(self, data):
                    with self._lock:
                        pass

                def flush(self):
                    # log -> server, while publish does server -> log
                    with self._lock:
                        SRV.wake()

            LOG = Log()
        '''),
        ("server.py", '''
            import threading
            from liblog import LOG

            class Server:
                def __init__(self):
                    self._lock = threading.Lock()

                def wake(self):
                    with self._lock:
                        pass

                def publish(self, data):
                    with self._lock:
                        LOG.append(data)

            SRV = Server()
        '''))
    inv = [f for f in findings if f.rule == "lock-order"]
    assert inv, findings
    assert "Log._lock" in inv[0].message and "Server._lock" in inv[0].message


def test_lock_order_rlock_reentry_is_a_non_finding(tmp_path):
    assert _lint(tmp_path, ("re.py", '''
        import threading

        class R:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
    ''')) == []


def test_lock_order_plain_lock_reentry_fires(tmp_path):
    findings = _lint(tmp_path, ("self.py", '''
        import threading

        class R:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
    '''))
    inv = [f for f in findings if f.rule == "lock-order"]
    assert inv and "self-deadlock" in inv[0].message, findings


def test_lock_order_condition_aliases_its_wrapped_lock(tmp_path):
    # acquiring the Condition IS acquiring the wrapped plain lock:
    # lock -> cv re-entry must be caught as a self-deadlock
    findings = _lint(tmp_path, ("cv.py", '''
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)

            def poke(self):
                with self._lock:
                    with self._cv:
                        pass
    '''))
    inv = [f for f in findings if f.rule == "lock-order"]
    assert inv and "self-deadlock" in inv[0].message, findings


def test_lock_order_consistent_order_is_clean(tmp_path):
    assert _lint(tmp_path, ("ok.py", '''
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def one():
            with A:
                with B:
                    pass

        def two():
            with A:
                with B:
                    pass
    ''')) == []


def test_lock_order_suppression_works(tmp_path):
    src = TWO_LOCK_INVERSION.replace(
        "        with self._alock:\n            with self._block:",
        "        with self._alock:\n            # datlint: disable=lock-order"
        "\n            with self._block:")
    assert _lint(tmp_path, ("inv.py", src)) == []


# -- blocking-under-lock: each blocked class ---------------------------------

def _blocking_fixture(body):
    return f'''
import os
import socket
import subprocess
import threading
import time

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self.sock = socket.socket()
        self.on_data = None

    def run(self, fd, cb, data):
        with self._lock:
{textwrap.indent(textwrap.dedent(body), "            ")}
'''


@pytest.mark.parametrize("body,cls", [
    ("self.sock.sendall(data)", "socket"),
    ("os.write(fd, data)", "os-io"),
    ("time.sleep(0.1)", "sleep"),
    ("subprocess.run(['true'])", "subprocess"),
    ("open('/tmp/x', 'wb')", "file-io"),
    ("cb(data)", "callback"),           # a parameter IS user code
    ("self.on_data(data)", "callback"),  # on_* attribute ditto
])
def test_blocking_under_lock_fires_per_class(tmp_path, body, cls):
    findings = _lint(tmp_path, ("b.py", _blocking_fixture(body)))
    hits = [f for f in findings if f.rule == "blocking-under-lock"]
    assert hits, (body, findings)
    assert f"[{cls}]" in hits[0].message


def test_blocking_under_lock_propagates_through_calls(tmp_path):
    # the helper contains no `with` at all — only the call graph knows
    # it runs locked (the single-file blind spot, closed)
    findings = _lint(tmp_path, ("t.py", '''
        import threading
        import time

        _lock = threading.Lock()

        def helper():
            time.sleep(1)

        def entry():
            with _lock:
                helper()
    '''))
    hits = [f for f in findings if f.rule == "blocking-under-lock"]
    assert hits, findings
    assert "entry" in hits[0].message and "helper" in hits[0].message


def test_blocking_under_lock_clean_outside_lock(tmp_path):
    assert _lint(tmp_path, ("ok.py", '''
        import threading
        import time

        _lock = threading.Lock()

        def entry():
            with _lock:
                n = 1 + 1
            time.sleep(n)
    ''')) == []


def test_blocking_allow_marker_accepts_the_site(tmp_path):
    assert _lint(tmp_path, ("a.py", '''
        import threading
        import time

        _lock = threading.Lock()

        def entry():
            with _lock:
                # justified: <why>  datlint: allow-blocking-under-lock
                time.sleep(0.1)
    ''')) == []


def test_blocking_allow_marker_is_class_scoped(tmp_path):
    findings = _lint(tmp_path, ("a.py", '''
        import threading
        import time

        _lock = threading.Lock()

        def entry(sock, data):
            with _lock:
                # datlint: allow-blocking-under-lock(sleep)
                time.sleep(0.1)
                sock.sendall(data)
    '''))
    hits = [f for f in findings if f.rule == "blocking-under-lock"]
    # the scoped allow covers sleep but NOT the socket write
    assert len(hits) == 1 and "[socket]" in hits[0].message, findings


def test_blocking_allow_is_lexical_only(tmp_path):
    """An allow next to the blocking site excuses only the locks
    VISIBLE there: a lock smuggled in by a caller still reports, so an
    audited leaf can never silently cover new locked callers."""
    findings = _lint(tmp_path, ("leaf.py", '''
        import threading
        import time

        _outer = threading.Lock()

        def leaf():
            # datlint: allow-blocking-under-lock
            time.sleep(0.1)

        def caller():
            with _outer:
                leaf()
    '''))
    hits = [f for f in findings if f.rule == "blocking-under-lock"]
    assert hits and "_outer" in hits[0].message, findings


def test_blocking_allow_at_call_site_covers_the_callee(tmp_path):
    # the sink-serializer idiom: the lock is held around a helper whose
    # entire job is the I/O it guards — the allow goes ON THE CALL
    assert _lint(tmp_path, ("sink.py", '''
        import threading
        import time

        class Sink:
            def __init__(self):
                self._lock = threading.Lock()

            def _io(self, data):
                time.sleep(0.1)

            def write(self, data):
                with self._lock:
                    # serializing is this lock's job:
                    # datlint: allow-blocking-under-lock
                    self._io(data)
    ''')) == []


# -- guarded-state ------------------------------------------------------------

GUARDED_BAD = '''
import threading

class Table:
    # datlint: guarded-by(self._lock): self._rows
    def __init__(self):
        self._lock = threading.Lock()
        self._rows = {}

    def put(self, k, v):
        with self._lock:
            self._rows[k] = v

    def forgot(self, k):
        self._rows[k] = None
'''


def test_guarded_state_fires_on_unguarded_write(tmp_path):
    findings = _lint(tmp_path, ("g.py", GUARDED_BAD))
    hits = [f for f in findings if f.rule == "guarded-state"]
    assert hits and "forgot" in hits[0].message, findings
    # the guarded write and the __init__ construction are NOT findings
    assert len(hits) == 1


def test_guarded_state_accepts_locked_helper_via_call_graph(tmp_path):
    # the *_locked idiom: no lexical `with`, but every known caller
    # holds the lock — proven through the entry-held fixpoint
    assert _lint(tmp_path, ("h.py", '''
        import threading

        class Table:
            # datlint: guarded-by(self._lock): self._rows
            def __init__(self):
                self._lock = threading.Lock()
                self._rows = {}

            def put(self, k, v):
                with self._lock:
                    self._put_locked(k, v)

            def drop(self, k):
                with self._lock:
                    self._put_locked(k, None)

            def _put_locked(self, k, v):
                self._rows[k] = v
    ''')) == []


def test_guarded_state_rejects_helper_with_one_unlocked_caller(tmp_path):
    findings = _lint(tmp_path, ("h.py", '''
        import threading

        class Table:
            # datlint: guarded-by(self._lock): self._rows
            def __init__(self):
                self._lock = threading.Lock()
                self._rows = {}

            def put(self, k, v):
                with self._lock:
                    self._put_locked(k, v)

            def sneaky(self, k):
                self._put_locked(k, None)

            def _put_locked(self, k, v):
                self._rows[k] = v
    '''))
    hits = [f for f in findings if f.rule == "guarded-state"]
    assert hits, findings


def test_guarded_state_counts_container_mutators_as_writes(tmp_path):
    findings = _lint(tmp_path, ("m.py", '''
        import threading

        class Q:
            # datlint: guarded-by(self._lock): self._items
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def ok(self, x):
                with self._lock:
                    self._items.append(x)

            def bad(self, x):
                self._items.append(x)
    '''))
    hits = [f for f in findings if f.rule == "guarded-state"]
    assert len(hits) == 1 and "mutator:append" in hits[0].message, findings


def test_guarded_state_suppression_works(tmp_path):
    src = GUARDED_BAD.replace(
        "        self._rows[k] = None",
        "        # single-threaded teardown: datlint: disable=guarded-state"
        "\n        self._rows[k] = None")
    assert _lint(tmp_path, ("g.py", src)) == []


# the cursor-coherence lesson: a declaration the rule cannot honor is
# LOUD, never a silent disarm
@pytest.mark.parametrize("old,new,needle", [
    # unparsable member: the whole declaration is ignored, loudly
    ("guarded-by(self._lock): self._rows",
     "guarded-by(self._lock): self._rows ,, junk(",
     "unparsable member"),
    # lock name that resolves to no known lock
    ("guarded-by(self._lock): self._rows",
     "guarded-by(self._no_such_lock): self._rows",
     "does not resolve"),
    # member no function ever writes: stale/typo'd spelling
    ("guarded-by(self._lock): self._rows",
     "guarded-by(self._lock): self._typo_rows",
     "ever writes it"),
])
def test_guarded_state_unhonorable_declarations_are_loud(
        tmp_path, old, new, needle):
    src = GUARDED_BAD.replace(old, new)
    findings = _lint(tmp_path, ("g.py", src))
    msgs = [f.message for f in findings if f.rule == "guarded-state"]
    assert any(needle in m for m in msgs), (needle, findings)


def test_guarded_state_self_member_outside_class_is_loud(tmp_path):
    findings = _lint(tmp_path, ("mod.py", '''
        import threading

        _lock = threading.Lock()
        # datlint: guarded-by(_lock): self._rows

        def f():
            pass
    '''))
    msgs = [f.message for f in findings if f.rule == "guarded-state"]
    assert any("outside any class" in m for m in msgs), findings


def test_guarded_state_module_level_globals(tmp_path):
    findings = _lint(tmp_path, ("mod.py", '''
        import threading

        _lock = threading.Lock()
        _cache = {}
        # datlint: guarded-by(_lock): _cache

        def ok(k, v):
            global _cache
            with _lock:
                _cache = {k: v}

        def bad(k):
            global _cache
            _cache = {}
    '''))
    hits = [f for f in findings if f.rule == "guarded-state"]
    assert len(hits) == 1 and "bad" in hits[0].message, findings


# -- wire-dispatch-parity -----------------------------------------------------

WIRE_OK = (
    ("framing.py", '''
        TYPE_HEADER = 0
        TYPE_CHANGE = 1
        TYPE_BLOB = 2
        KNOWN_TYPES = (TYPE_CHANGE, TYPE_BLOB)
    '''),
    ("decoder.py", '''
        from framing import TYPE_BLOB, TYPE_CHANGE

        def trace(kind):
            pass

        class Decoder:
            def __init__(self):
                self.changes = 0
                self.blobs = 0

            def _scan_header(self, type_id):
                if type_id == TYPE_CHANGE:
                    trace(kind="change")
                elif type_id == TYPE_BLOB:
                    trace(kind="blob")

            def _run_indexed(self, ids):
                for type_id in ids:
                    if type_id == TYPE_CHANGE:
                        self.changes += 1
                    elif type_id == TYPE_BLOB:
                        self.blobs += 1

            def _frames_delivered(self):
                return self.changes + self.blobs
    '''),
)


def _wire_lint(tmp_path, *files):
    from dat_replication_protocol_tpu.analysis.rules.wire_dispatch import (
        WireDispatchParity,
    )

    return _lint(tmp_path, *files, rules=[WireDispatchParity()])


def test_wire_dispatch_full_matrix_is_clean(tmp_path):
    assert _wire_lint(tmp_path, *WIRE_OK) == []


def test_wire_dispatch_fires_when_scanner_misses_a_type(tmp_path):
    framing = ("framing.py", WIRE_OK[0][1].replace(
        "KNOWN_TYPES = (TYPE_CHANGE, TYPE_BLOB)",
        "TYPE_NEW = 3\n        "
        "KNOWN_TYPES = (TYPE_CHANGE, TYPE_BLOB, TYPE_NEW)"))
    findings = _wire_lint(tmp_path, framing, WIRE_OK[1])
    msgs = [f.message for f in findings
            if f.rule == "wire-dispatch-parity"]
    assert any("TYPE_NEW" in m and "half-wired" in m
               and "_scan_header" in m for m in msgs), findings


def test_wire_dispatch_fires_per_missing_surface(tmp_path):
    # TYPE_BLOB wired into the scanner only: bulk, accounting, and
    # tracing must all be named missing
    decoder = ("decoder.py", '''
        from framing import TYPE_BLOB, TYPE_CHANGE

        def trace(kind):
            pass

        class Decoder:
            def __init__(self):
                self.changes = 0

            def _scan_header(self, type_id):
                if type_id == TYPE_CHANGE:
                    trace(kind="change")
                elif type_id == TYPE_BLOB:
                    pass

            def _run_indexed(self, ids):
                for type_id in ids:
                    if type_id == TYPE_CHANGE:
                        self.changes += 1

            def _frames_delivered(self):
                return self.changes
    ''')
    findings = _wire_lint(tmp_path, WIRE_OK[0], decoder)
    msgs = [f.message for f in findings
            if f.rule == "wire-dispatch-parity" and "TYPE_BLOB" in f.message]
    assert msgs, findings
    m = msgs[0]
    assert "_run_indexed" in m and "_frames_delivered" in m \
        and 'kind="blob"' in m


def test_wire_dispatch_type_outside_known_types_is_loud(tmp_path):
    framing = ("framing.py",
               WIRE_OK[0][1].rstrip() + "\n        TYPE_ROGUE = 9\n")
    findings = _wire_lint(tmp_path, framing, WIRE_OK[1])
    msgs = [f.message for f in findings
            if f.rule == "wire-dispatch-parity"]
    assert any("TYPE_ROGUE" in m and "KNOWN_TYPES" in m for m in msgs)


def test_wire_dispatch_lost_anchor_is_loud(tmp_path):
    # renaming _scan_header must not silently disarm the matrix
    decoder = ("decoder.py", WIRE_OK[1][1].replace(
        "_scan_header", "_scan_hdr"))
    findings = _wire_lint(tmp_path, WIRE_OK[0], decoder)
    msgs = [f.message for f in findings
            if f.rule == "wire-dispatch-parity"]
    assert any("lost its anchor" in m for m in msgs), findings


# -- structured CLI -----------------------------------------------------------

def test_cli_json_output_carries_chains(tmp_path, capsys):
    (tmp_path / "inv.py").write_text(textwrap.dedent(TWO_LOCK_INVERSION))
    rc = datlint_main([str(tmp_path), "--rule", "lock-order", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["findings"], out
    f = out["findings"][0]
    assert set(f) == {"rule", "path", "line", "message", "chains"}
    assert f["rule"] == "lock-order" and len(f["chains"]) == 2


def test_cli_baseline_accepts_known_findings(tmp_path, capsys):
    (tmp_path / "inv.py").write_text(textwrap.dedent(TWO_LOCK_INVERSION))
    base = tmp_path / "baseline.json"
    rc = datlint_main([str(tmp_path), "--rule", "lock-order",
                       "--write-baseline", str(base)])
    assert rc == 0 and json.loads(base.read_text())["accept"]
    capsys.readouterr()
    # accepted: the same findings no longer fail the run
    rc = datlint_main([str(tmp_path), "--rule", "lock-order",
                       "--baseline", str(base)])
    assert rc == 0
    assert "baseline-accepted" in capsys.readouterr().out
    # ...but a NEW finding still does
    (tmp_path / "new.py").write_text(textwrap.dedent('''
        import threading

        class N:
            def __init__(self):
                self._xlock = threading.Lock()
                self._ylock = threading.Lock()

            def f(self):
                with self._xlock:
                    with self._ylock:
                        pass

            def g(self):
                with self._ylock:
                    with self._xlock:
                        pass
    '''))
    rc = datlint_main([str(tmp_path), "--rule", "lock-order",
                       "--baseline", str(base)])
    assert rc == 1


def test_cli_unreadable_baseline_is_a_usage_error(tmp_path):
    (tmp_path / "ok.py").write_text("x = 1\n")
    bad = tmp_path / "broken.json"
    bad.write_text("{not json")
    assert datlint_main([str(tmp_path), "--baseline", str(bad)]) == 2


def test_cli_stats_reports_per_rule_time(tmp_path, capsys):
    (tmp_path / "ok.py").write_text("x = 1\n")
    rc = datlint_main([str(tmp_path), "--rule", "lock-order", "--stats"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "stats: lock-order:" in out and "stats: TOTAL:" in out


def test_cli_lock_graph_is_deterministic(tmp_path, capsys):
    (tmp_path / "l.py").write_text(textwrap.dedent('''
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def f(self):
                with self._lock:
                    pass
    '''))
    g1, g2 = tmp_path / "g1.json", tmp_path / "g2.json"
    datlint_main([str(tmp_path / "l.py"), "--lock-graph", str(g1)])
    datlint_main([str(tmp_path / "l.py"), "--lock-graph", str(g2)])
    capsys.readouterr()
    assert g1.read_bytes() == g2.read_bytes()
    doc = json.loads(g1.read_text())
    assert doc["locks"] and doc["locks"][0]["id"] == "l.py::A._lock"


# -- regression tests for the true positives fixed in production -------------
#
# Each of these encodes the post-fix behavior of a finding the
# whole-program pass produced on the real tree (ANALYSIS.md table).
# The aggregate guard is test_datlint_repo_clean.py; these pin the
# BEHAVIOR the fixes must preserve.

def test_fanout_trim_event_survives_the_deferred_emit(obs_enabled):
    # fanout.trim used to be emitted INSIDE the log lock; it now rides
    # _maybe_trim_locked's return value out — same event, lock released
    from dat_replication_protocol_tpu.fanout.log import BroadcastLog
    from dat_replication_protocol_tpu.obs.events import EVENTS

    log = BroadcastLog(retention_budget=64)
    log.append(b"x" * 256)
    log.enforce_retention()
    trims = EVENTS.events("fanout.trim")
    assert trims, "retention trim no longer emits fanout.trim"
    assert trims[-1]["fields"]["trimmed"] > 0


def test_fanout_attach_refusal_still_emits_snapshot_needed(obs_enabled):
    from dat_replication_protocol_tpu.fanout.log import (
        BroadcastLog,
        SnapshotNeeded,
    )
    from dat_replication_protocol_tpu.obs.events import EVENTS

    log = BroadcastLog(retention_budget=64)
    log.append(b"x" * 256)
    log.enforce_retention()
    with pytest.raises(SnapshotNeeded):
        log.attach("late", 0)
    evs = EVENTS.events("fanout.snapshot_needed")
    assert evs and evs[-1]["fields"]["offset"] == 0


def test_eventlog_clear_resets_sink_dropped_under_its_own_lock():
    # clear() used to reset sink_dropped under _lock while the sink
    # path increments it under _sink_lock — a lost-update the
    # guarded-state declaration now forbids
    from dat_replication_protocol_tpu.obs.events import EventLog

    log = EventLog(capacity=4)
    log.sink_dropped = 3
    log.dropped = 2
    log.clear()
    assert log.sink_dropped == 0 and log.dropped == 0


def test_attach_peer_dup_failure_rolls_back_the_cursor(monkeypatch):
    # os.dup moved INSIDE the rollback scope: an EMFILE after
    # log.attach must detach the provisional cursor, or the peer key
    # is unusable until process restart
    import os
    import socket

    from dat_replication_protocol_tpu.fanout.log import BroadcastLog
    from dat_replication_protocol_tpu.fanout.server import FanoutServer

    log = BroadcastLog()
    log.append(b"x" * 64)
    srv = FanoutServer(log)
    a, b = socket.socketpair()
    try:
        def _emfile(fd):
            raise OSError(24, "Too many open files")

        monkeypatch.setattr(os, "dup", _emfile)
        with pytest.raises(OSError):
            srv.attach_peer("k", fd=a.fileno(), offset=0)
        monkeypatch.undo()
        # the key must be reusable: the provisional cursor was detached
        peer = srv.attach_peer("k", sink=lambda views: sum(
            len(v) for v in views), offset=0)
        srv.seal()
        assert srv.drain()
        assert peer.wait_done()
    finally:
        srv.close()
        a.close()
        b.close()


def test_guarded_state_baseline_keys_are_line_number_free(tmp_path):
    # the declaration site lives in the finding's SECOND sentence:
    # Finding.key() keeps only the first, so a --baseline entry must
    # survive unrelated edits shifting the guarded-by line
    import re

    shifted = GUARDED_BAD.replace(
        "import threading", "import threading\n\nPAD = 1\n")
    k1 = [f.key() for f in _lint(tmp_path, ("g1.py", GUARDED_BAD))
          if f.rule == "guarded-state"]
    k2 = [f.key() for f in _lint(tmp_path, ("g1.py", shifted))
          if f.rule == "guarded-state"]
    assert k1 and k1 == k2
    assert not re.search(r":\d+", k1[0].split(":", 1)[1])


def test_index_sees_defs_and_locks_in_except_handlers(tmp_path):
    # the import-shim idiom: the fallback def lives in the EXCEPT
    # handler (utils/jax_compat.py shape) — it must be in the call
    # graph, or blocking under a lock through it goes dark
    findings = _lint(tmp_path, ("shim.py", '''
import threading
import time

_lock = threading.Lock()

try:
    from nonexistent_fast_mod import helper
except ImportError:
    def helper():
        time.sleep(0.1)

def run():
    with _lock:
        helper()
'''))
    hits = [f for f in findings if f.rule == "blocking-under-lock"]
    assert hits, findings
    assert "[sleep]" in hits[0].message


def test_blocking_sees_with_item_calls(tmp_path):
    # `with open(...)` / `with helper():` — the call lives in the
    # with-ITEM expression, which the walk used to drop entirely
    findings = _lint(tmp_path, ("w.py", '''
import threading
import time

_lock = threading.Lock()

def helper():
    time.sleep(0.1)
    class _N:
        def __enter__(self): return self
        def __exit__(self, *a): return False
    return _N()

def direct(path):
    with _lock:
        with open(path, "w"):
            pass

def through_manager():
    with _lock:
        with helper():
            pass
'''))
    hits = [f for f in findings if f.rule == "blocking-under-lock"]
    classes = {m for f in hits for m in ("[file-io]", "[sleep]")
               if m in f.message}
    assert classes == {"[file-io]", "[sleep]"}, hits


def test_cli_stats_prints_with_write_baseline(tmp_path, capsys):
    (tmp_path / "c.py").write_text(textwrap.dedent(TWO_LOCK_INVERSION))
    rc = datlint_main([str(tmp_path / "c.py"), "--stats",
                       "--write-baseline", str(tmp_path / "b.json")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "datlint: stats: TOTAL:" in out and "wrote" in out


def test_attach_peer_duplicate_key_is_a_server_level_error():
    from dat_replication_protocol_tpu.fanout.log import BroadcastLog
    from dat_replication_protocol_tpu.fanout.server import FanoutServer

    log = BroadcastLog()
    log.append(b"x" * 16)
    srv = FanoutServer(log)
    try:
        srv.attach_peer("k", sink=lambda vs: sum(len(v) for v in vs),
                        offset=0)
        with pytest.raises(ValueError, match="peer key 'k' already"):
            srv.attach_peer("k", sink=lambda vs: 0, offset=0)
    finally:
        srv.close()


def test_guarded_state_accepts_function_local_lock_alias(tmp_path):
    # 'mu = self._mu; with mu:' — the mutator write's held set comes
    # from the main walk (aliases resolved), not a lexical re-walk
    findings = _lint(tmp_path, ("a.py", '''
import threading

class Box:
    # datlint: guarded-by(self._mu): self._items
    def __init__(self):
        self._mu = threading.Lock()
        self._items = []

    def put(self, x):
        mu = self._mu
        with mu:
            self._items.append(x)
'''))
    assert not [f for f in findings if f.rule == "guarded-state"], findings


def test_cli_baseline_keys_survive_path_spelling(tmp_path, capsys):
    (tmp_path / "m.py").write_text(textwrap.dedent(TWO_LOCK_INVERSION))
    base = tmp_path / "b.json"
    # record with a RELATIVE spelling, accept with the ABSOLUTE one
    import os
    old = os.getcwd()
    os.chdir(tmp_path)
    try:
        datlint_main(["m.py", "--write-baseline", str(base)])
    finally:
        os.chdir(old)
    rc = datlint_main([str(tmp_path / "m.py"), "--baseline", str(base)])
    out = capsys.readouterr().out
    assert rc == 0 and "baseline-accepted" in out


def test_attach_peer_bad_offset_is_not_reported_as_duplicate():
    from dat_replication_protocol_tpu.fanout.log import BroadcastLog
    from dat_replication_protocol_tpu.fanout.server import FanoutServer

    log = BroadcastLog()
    log.append(b"x" * 8)
    srv = FanoutServer(log)
    try:
        with pytest.raises(ValueError) as ei:
            srv.attach_peer("k", sink=lambda vs: 0, offset="abc")
        assert "already attached" not in str(ei.value)
    finally:
        srv.close()


def test_guarded_state_fires_inside_closed_call_cycles(tmp_path):
    # mutually-recursive helpers with no outside caller: the entry-held
    # fixpoint used to seed them with ALL locks and converge there,
    # silently accepting an unguarded write
    findings = _lint(tmp_path, ("cyc.py", '''
import threading

class Pair:
    # datlint: guarded-by(self._lock): self._n
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def ping(self, k):
        if k > 0:
            self.pong(k - 1)

    def pong(self, k):
        self._n = k
        self.ping(k)
'''))
    hits = [f for f in findings if f.rule == "guarded-state"]
    assert hits and "self._n" in hits[0].message, findings


def test_cli_json_with_write_baseline_emits_one_document(tmp_path, capsys):
    (tmp_path / "j.py").write_text(textwrap.dedent(TWO_LOCK_INVERSION))
    rc = datlint_main([str(tmp_path / "j.py"), "--json",
                       "--write-baseline", str(tmp_path / "b.json")])
    out = capsys.readouterr().out
    doc = json.loads(out)   # must parse as exactly one JSON document
    assert rc == 0 and doc["accepted_keys"] >= 1


def test_attach_peer_at_capacity_rejects_before_snapshot_redirect():
    # admission must stay the CHEAP first gate: a stale offset at a
    # full server gets FanoutBusy, not a SnapshotNeeded+hint redirect
    # into a snapshot fetch the full server would then reject
    from dat_replication_protocol_tpu.fanout.log import (
        BroadcastLog,
        SnapshotNeeded,
    )
    from dat_replication_protocol_tpu.fanout.server import (
        FanoutBusy,
        FanoutServer,
    )

    log = BroadcastLog(retention_budget=64)
    log.append(b"x" * 400)
    log.enforce_retention()   # offset 0 is now below the window
    srv = FanoutServer(log, max_peers=1, snapshot_hint={"port": 1})
    try:
        srv.attach_peer("a", sink=lambda vs: sum(len(v) for v in vs))
        with pytest.raises(FanoutBusy):
            try:
                srv.attach_peer("late", sink=lambda vs: 0, offset=0)
            except SnapshotNeeded:
                pytest.fail("full server redirected a joiner into the "
                            "snapshot protocol instead of FanoutBusy")
    finally:
        srv.close()
