"""Wire cost plane (ISSUE 20): the per-link byte ledger that EXACTLY
TILES the wire, its derived goodput/overhead/amplification watermarks,
the dark-twin bytecode discipline on every instrumented hot path, the
sender==receiver batch-savings parity (satellite 1), and the fleet
cost-matrix SLO gate.

The headline invariant is the 20-seed chaos oracle: across session
(faulty resumable transport), fan-out, and gossip legs, the sum of
per-class bytes (payload + framing) equals the transport/journal byte
ground truth, and the unattributed residual is EXACTLY 0 at
convergence.  Faults keep the last watermark and bump ``failures`` —
unknown is reported as unknown, never zero.
"""

from __future__ import annotations

import json
import random
import types

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu import CAP_CHANGE_BATCH
from dat_replication_protocol_tpu.cluster import ReplicaNode, gossip_exchange
from dat_replication_protocol_tpu.cluster import node as cluster_node
from dat_replication_protocol_tpu.fanout import FanoutServer
from dat_replication_protocol_tpu.fanout import server as fanout_server
from dat_replication_protocol_tpu.obs import fleet
from dat_replication_protocol_tpu.obs import metrics as obs_metrics
from dat_replication_protocol_tpu.obs.wirecost import WIRECOST, CLASSES
from dat_replication_protocol_tpu.session import decoder as decoder_mod
from dat_replication_protocol_tpu.session import encoder as encoder_mod
from dat_replication_protocol_tpu.session import pump as pump_mod
from dat_replication_protocol_tpu.session.faults import (
    FaultPlan,
    FaultyReader,
    TransportFault,
    bytes_reader,
)
from dat_replication_protocol_tpu.session.reconnect import (
    BackoffPolicy,
    run_resumable,
)
from dat_replication_protocol_tpu.session.resume import WireJournal


def _recs(lo: int, hi: int, tag: str = "s", val: bytes = b"v"):
    return [{"key": f"k{i}", "change": i, "from": 0, "to": 1,
             "value": val + b"%d" % i, "subset": tag}
            for i in range(lo, hi)]


def _ledger(link: str, direction: str) -> dict:
    return WIRECOST.snapshot()["links"][f"{link}|{direction}"]


def _build_wire(rng: random.Random):
    """One encoder session mixing every frame class the session layer
    emits: per-record changes, a coalesced batch, and a blob.  Returns
    (wire bytes, encoder)."""
    e = protocol.encode()
    j = WireJournal()
    e.attach_journal(j)
    n = rng.randrange(20, 60)
    for i in range(n):
        e.change({"key": f"k{i}", "change": i, "from": i, "to": i + 1,
                  "value": b"v" * rng.randrange(0, 40)})
    e.negotiate(CAP_CHANGE_BATCH)
    e.change_many([{"key": f"b{i}", "change": i, "from": 0, "to": 1,
                    "value": b"w" * rng.randrange(0, 20)}
                   for i in range(rng.randrange(10, 30))])
    e.flush_batch()
    blob_len = rng.randrange(50, 300)
    b = e.blob(blob_len)
    b.write(b"x" * blob_len)
    b.end()
    e.finalize()
    while e.read(4096) is not None:
        pass
    return j.read_from(0), e


# -- board unit layer ---------------------------------------------------------


def test_account_rejects_unknown_class_and_direction(obs_enabled):
    with pytest.raises(ValueError):
        WIRECOST.account("framing", "l", "tx", 1, 1)  # synthetic class
    with pytest.raises(ValueError):
        WIRECOST.account("change", "l", "out", 1, 1)


def test_watermarks_are_none_until_denominators_known(obs_enabled):
    WIRECOST.account("reconcile", "l", "tx", 100, 4)
    rec = _ledger("l", "tx")
    # transport never reported: the residual is unknown, not zero
    assert rec["residual_bytes"] is None
    # no completed peel yet: wire-per-diff-byte unknown
    assert rec["reconcile_wire_per_diff_byte"] is None
    assert rec["snapshot_cold_ratio"] is None
    WIRECOST.note_diff("l", "tx", 50)
    WIRECOST.note_transport("l", "tx", 104)
    rec = _ledger("l", "tx")
    assert rec["residual_bytes"] == 0
    assert rec["reconcile_wire_per_diff_byte"] == pytest.approx(104 / 50)


def test_goodput_and_overhead_tile_by_construction(obs_enabled):
    WIRECOST.account("change", "l", "rx", 90, 10)
    rec = _ledger("l", "rx")
    assert rec["ledger_bytes"] == 100
    assert rec["goodput_fraction"] == pytest.approx(0.9)
    assert rec["overhead_ratio"] == pytest.approx(0.1)
    assert rec["goodput_fraction"] + rec["overhead_ratio"] == 1.0


def test_failure_keeps_watermarks_and_bumps_counter(obs_enabled):
    WIRECOST.account("change", "l", "tx", 90, 10)
    before = _ledger("l", "tx")
    WIRECOST.note_failure("l", "tx", "TransportFault: injected")
    after = _ledger("l", "tx")
    assert after["failures"] == 1
    assert after["error"] == "TransportFault: injected"
    # the cost did not heal: every watermark holds its last value
    for key in ("ledger_bytes", "goodput_fraction", "overhead_ratio"):
        assert after[key] == before[key]


def test_collector_exports_labeled_counters_and_skips_none(obs_enabled):
    WIRECOST.account("change", "l", "tx", 90, 10, frames=3)
    snap = obs_metrics.snapshot()
    assert snap["counters"]["wire.cost.bytes{link=l,dir=tx,class=change}"] \
        == 90
    assert snap["counters"][
        "wire.cost.bytes{link=l,dir=tx,class=framing}"] == 10
    assert snap["counters"][
        "wire.cost.frames{link=l,dir=tx,class=change}"] == 3
    assert snap["gauges"]["wire.cost.goodput_fraction{link=l,dir=tx}"] \
        == pytest.approx(0.9)
    # transport unknown: the residual gauge must be ABSENT, not 0
    assert "wire.cost.residual_bytes{link=l,dir=tx}" not in snap["gauges"]


def test_amplification_view_and_gauge(obs_enabled):
    WIRECOST.note_source("fan", 100)
    WIRECOST.note_delivered("fan", "p1", 100)
    WIRECOST.note_delivered("fan", "p2", 100)
    amp = WIRECOST.snapshot()["amplification"]["fan"]
    assert amp["source_bytes"] == 100
    assert amp["delivered_bytes"] == 200
    assert amp["peers"] == {"p1": 100, "p2": 100}
    assert amp["amplification"] == pytest.approx(2.0)
    snap = obs_metrics.snapshot()
    assert snap["gauges"]["wire.cost.amplification{link=fan}"] \
        == pytest.approx(2.0)
    assert snap["counters"][
        "wire.cost.delivered_bytes{link=fan,peer=p1}"] == 100


def test_snapshot_is_jsonable(obs_enabled):
    WIRECOST.account("snapshot", "l", "tx", 10, 2)
    WIRECOST.note_dataset("l", "tx", 1000)
    WIRECOST.note_source("fan", 10)
    json.dumps(WIRECOST.snapshot())


# -- session tiling (direct feed: ledger vs encoder/decoder cursors) ----------


def test_session_ledger_tiles_encoder_and_decoder_exactly(obs_enabled):
    wire, enc = _build_wire(random.Random(7))
    tx = _ledger("session", "tx")
    assert tx["ledger_bytes"] == enc.bytes == len(wire)
    assert set(tx["classes"]) == {"change", "change_batch", "blob"}
    dec = protocol.decode()
    dec.change(lambda c, done: done())
    dec.blob(lambda blob, done: blob.collect(lambda _d: done()))
    for off in range(0, len(wire), 97):
        dec.write(wire[off:off + 97])
    rx = _ledger("session", "rx")
    assert rx["ledger_bytes"] == dec.bytes == len(wire)
    # class-by-class: both ends attributed the SAME frames
    for cls in tx["classes"]:
        assert tx["classes"][cls]["payload"] + tx["classes"][cls][
            "framing"] == rx["classes"][cls]["payload"] + rx["classes"][
            cls]["framing"], cls


def test_batch_savings_sender_equals_receiver(obs_enabled):
    """Satellite 1: the decoder recomputes the batch savings from the
    decoded columns with the SAME estimate arithmetic the encoder used
    pre-encode — the cross-check is an equality, not a proxy."""
    e = protocol.encode(peer_caps=CAP_CHANGE_BATCH)
    # rows sharing one subset tag: the columnar shape the batch frame
    # actually compresses (the tag is encoded once, not per row)
    e.change_many([{"key": f"k{i}", "change": i, "from": 0, "to": 1,
                    "value": b"v" * (i % 9),
                    "subset": "dataset/shared-tag"} for i in range(60)])
    e.finalize()
    chunks = []
    while True:
        d = e.read(4096)
        if d is None:
            break
        if d:
            chunks.append(d)
    wire = b"".join(chunks)
    dec = protocol.decode()
    dec.change(lambda c, done: done())
    dec.write(wire)
    tx, rx = _ledger("session", "tx"), _ledger("session", "rx")
    assert tx["batch_saved_bytes"] > 0
    assert tx["batch_saved_bytes"] == rx["batch_saved_bytes"]
    snap = obs_metrics.snapshot()
    assert snap["counters"]["wire.batch.bytes_saved"] == \
        snap["counters"]["wire.batch.bytes_saved_rx"]


def test_decoder_failure_is_recorded_on_the_ledger(obs_enabled):
    from dat_replication_protocol_tpu.wire import frame
    dec = protocol.decode()
    errs = []
    dec.on_error(lambda e: errs.append(e))
    dec.write(frame(7, b"xx"))  # unknown type id: structured wire error
    assert dec.destroyed and errs
    rec = WIRECOST.snapshot()["links"].get("session|rx")
    assert rec is not None and rec["failures"] >= 1
    assert "unknown type" in rec["error"]


# -- the chaos oracle (20 seeds: session + fanout + gossip) -------------------


@pytest.mark.parametrize("seed", range(20))
def test_chaos_ledger_exactly_tiles_the_wire(obs_enabled, seed):
    rng = random.Random(seed)

    # session leg: a faulty, resuming transport — at convergence the
    # receive ledger covers every wire byte exactly once
    wire, enc = _build_wire(rng)
    assert _ledger("session", "tx")["ledger_bytes"] == len(wire)
    dec = protocol.decode()
    dec.change(lambda c, done: done())
    dec.blob(lambda blob, done: blob.collect(lambda _d: done()))

    def source(ckpt, failures):
        plan = FaultPlan(
            seed=seed * 31 + failures, max_segment=64,
            drop_at=(len(wire) // 2 - ckpt.wire_offset)
            if failures == 0 else None)
        return FaultyReader(bytes_reader(wire[ckpt.wire_offset:]), plan)

    stats = run_resumable(source, dec,
                          BackoffPolicy(base=0, max_retries=3, seed=1),
                          expected_total=len(wire))
    assert stats["reconnects"] == 1
    rx = _ledger("session", "rx")
    assert rx["ledger_bytes"] == len(wire), \
        f"seed {seed}: rx ledger {rx['ledger_bytes']} != wire {len(wire)}"

    # fanout leg: source intake vs per-peer delivered — amplification
    # is exactly the peer count once every peer drained
    n_peers = rng.randrange(2, 5)
    srv = FanoutServer(stall_timeout=10.0)
    try:
        bufs = [bytearray() for _ in range(n_peers)]
        def _sink(buf):
            def sink(views):
                n = 0
                for v in views:
                    buf.extend(bytes(v))
                    n += len(v)
                return n
            return sink
        peers = [srv.attach_peer(f"p{i}", sink=_sink(bufs[i]))
                 for i in range(n_peers)]
        step = rng.randrange(500, 4000)
        for off in range(0, len(wire), step):
            srv.publish(wire[off:off + step])
        srv.seal()
        assert srv.drain(15)
        for p in peers:
            assert p.wait_done(5)
    finally:
        srv.close()
    amp = WIRECOST.snapshot()["amplification"]["fanout"]
    assert amp["source_bytes"] == len(wire)
    assert amp["delivered_bytes"] == n_peers * len(wire)
    assert amp["amplification"] == pytest.approx(n_peers)

    # gossip leg: the exchange's own wire meter is the ground truth —
    # reconcile + repair-batch classes tile it, residual exactly 0
    lo = rng.randrange(0, 30)
    a = ReplicaNode("a", _recs(lo, lo + 40))
    b = ReplicaNode("b", _recs(lo + 20, lo + 60))
    res = gossip_exchange(a, b)
    assert res["ok"]
    for link in ("a->b", "b->a"):
        rec = _ledger(link, "tx")
        assert rec["residual_bytes"] == 0, f"seed {seed} link {link}"
        assert rec["transport_bytes"] > 0

    # fault arm: a dropped exchange keeps the last watermark and bumps
    # failures — the ledger never heals itself on a fault
    before = _ledger("a->b", "tx")
    with pytest.raises(TransportFault):
        gossip_exchange(a, b, plan_out=FaultPlan(seed=seed, drop_at=10))
    after = _ledger("a->b", "tx")
    assert after["failures"] == before["failures"] + 1
    assert after["ledger_bytes"] == before["ledger_bytes"]
    assert after["goodput_fraction"] == before["goodput_fraction"]


# -- dark-twin bytecode discipline (the PR 18/19 contract) --------------------


def _all_names(code) -> set:
    names = set(code.co_names)
    for c in code.co_consts:
        if isinstance(c, types.CodeType):
            names |= _all_names(c)
    return names


# every forked hot path: its bytecode (closures included) must reference
# no symbol of the wirecost module — the dark cost of the whole plane is
# one attribute load per fork point
DARK_TWINS = [
    encoder_mod.Encoder.flush_batch,
    encoder_mod.Encoder.change_many,
    encoder_mod.Encoder._frame_change,
    encoder_mod.Encoder.reconcile_frame,
    encoder_mod.Encoder.snapshot_frame,
    encoder_mod.Encoder.blob,
    encoder_mod.BlobWriter._uncork,
    decoder_mod.Decoder._deliver_change,
    decoder_mod.Decoder._finish_change_batch,
    decoder_mod.Decoder._dispatch_changes_fast,
    decoder_mod.Decoder._run_indexed,
    decoder_mod.Decoder.write_indexed,
    decoder_mod.Decoder._finish_reconcile,
    decoder_mod.Decoder._finish_snapshot,
    decoder_mod.Decoder._open_blob_if_ready,
    decoder_mod.Decoder._protocol_error,
    pump_mod.recv_pump,
    pump_mod.send_pump,
    pump_mod.recv_step,
    pump_mod.send_step,
    pump_mod._recv_step_py,
    pump_mod._send_step_impl,
    fanout_server.FanoutServer.publish,
    fanout_server.FanoutServer._serve_peer,
    cluster_node._exchange,
]

# the lit twins: each MUST reference the wirecost module — proof the
# fork actually routes cost recording through them
LIT_TWINS = [
    encoder_mod.Encoder._lit_cost_change,
    encoder_mod.Encoder._lit_cost_batch,
    encoder_mod.Encoder._lit_cost_reconcile,
    encoder_mod.Encoder._lit_cost_snapshot,
    encoder_mod.Encoder._lit_cost_blob,
    decoder_mod.Decoder._lit_cost_change,
    decoder_mod.Decoder._lit_cost_change_run,
    decoder_mod.Decoder._lit_cost_batch,
    decoder_mod.Decoder._lit_cost_reconcile,
    decoder_mod.Decoder._lit_cost_snapshot,
    decoder_mod.Decoder._lit_cost_blob,
    decoder_mod.Decoder._lit_cost_failure,
    pump_mod._lit_rx,
    pump_mod._lit_tx,
    fanout_server.FanoutServer._lit_cost_published,
    fanout_server.FanoutServer._lit_cost_served,
    cluster_node._exchange_lit,
]


@pytest.mark.parametrize(
    "fn", DARK_TWINS,
    ids=[f.__qualname__ for f in DARK_TWINS])
def test_hot_path_bytecode_references_no_wirecost_symbol(fn):
    names = _all_names(fn.__code__)
    assert not any("wirecost" in n for n in names), \
        f"{fn.__qualname__} references {sorted(n for n in names if 'wirecost' in n)}"


@pytest.mark.parametrize(
    "fn", LIT_TWINS,
    ids=[f.__qualname__ for f in LIT_TWINS])
def test_lit_twin_bytecode_references_wirecost(fn):
    assert any("wirecost" in n for n in _all_names(fn.__code__)), \
        f"{fn.__qualname__} never reaches the wirecost board"


def test_dark_path_records_nothing(obs_enabled):
    obs_metrics.OBS.on = False
    wire, _enc = _build_wire(random.Random(1))
    dec = protocol.decode()
    dec.change(lambda c, done: done())
    dec.blob(lambda blob, done: blob.collect(lambda _d: done()))
    dec.write(wire)
    snap = WIRECOST.snapshot()
    assert snap["links"] == {} and snap["amplification"] == {}


# -- sidecar presence gating + fleet cost-matrix SLO --------------------------


def test_sidecar_snapshot_gates_wirecost_on_presence(obs_enabled):
    from dat_replication_protocol_tpu import sidecar
    assert "wirecost" not in sidecar.snapshot_stats()
    WIRECOST.account("change", "s", "tx", 10, 2)
    assert "wirecost" in sidecar.snapshot_stats()
    assert "s|tx" in sidecar.snapshot_stats()["wirecost"]["links"]


def _target_with(wc):
    snap = {"ts": 0.0, "monotonic": 0.0,
            "metrics": {"counters": {}, "gauges": {}},
            "events_dropped": 0, "jit_sites": {},
            "watermarks": {"cursors": {}, "marks": {}}}
    if wc is not None:
        snap["wirecost"] = wc
    return lambda: snap


def test_fleet_slo_passes_on_clean_cost_matrix(obs_enabled):
    a = ReplicaNode("a", _recs(0, 40))
    b = ReplicaNode("b", _recs(20, 60))
    gossip_exchange(a, b)
    WIRECOST.note_source("fanout", 500)
    WIRECOST.note_delivered("fanout", "p1", 500)
    view = fleet.FleetView([_target_with(WIRECOST.snapshot())])
    sample = view.poll()
    rows = fleet.evaluate_slo(
        {"min_goodput_fraction": 0.5, "max_overhead_ratio": 0.5,
         "max_egress_bytes_per_peer": 10_000}, sample)
    assert rows and all(r["status"] == "ok" for r in rows)
    checks = {r["check"] for r in rows}
    assert checks == {"min_goodput_fraction", "max_overhead_ratio",
                      "max_egress_bytes_per_peer"}
    # the dashboard renders the cost matrix
    frame = fleet.render_dashboard(view, sample)
    assert "cost link" in frame and "amplification fanout" in frame


def test_fleet_slo_names_the_doctored_link(obs_enabled):
    wc = {"links": {"bad|tx": {
        "classes": {"change": {"payload": 10, "framing": 90, "frames": 9}},
        "ledger_bytes": 100, "payload_bytes": 10, "framing_bytes": 90,
        "goodput_fraction": 0.1, "overhead_ratio": 0.9,
        "batch_saved_bytes": 0, "residual_bytes": 0,
        "transport_bytes": 100, "failures": 0}}, "amplification": {}}
    sample = fleet.FleetView([_target_with(wc)]).poll()
    rows = fleet.evaluate_slo(
        {"min_goodput_fraction": 0.5, "max_overhead_ratio": 0.5}, sample)
    fails = [r for r in rows if r["status"] == "fail"]
    assert len(fails) == 2
    assert all(r["subject"] == "bad|tx" for r in fails)


def test_fleet_slo_fails_loud_when_cost_plane_dark(obs_enabled):
    sample = fleet.FleetView([_target_with(None)]).poll()
    for slo in ({"min_goodput_fraction": 0.5},
                {"max_overhead_ratio": 0.5},
                {"max_egress_bytes_per_peer": 100}):
        rows = fleet.evaluate_slo(slo, sample)
        assert any(r["check"] == "wirecost" and r["status"] == "fail"
                   for r in rows), slo


def test_fleet_slo_fails_on_unknown_ratio_not_passes(obs_enabled):
    # a link with no bytes attributed: ratio None — evaluated as a
    # failure, never a free pass (unknown is not zero)
    wc = {"links": {"mute|rx": {
        "classes": {}, "ledger_bytes": 0, "payload_bytes": 0,
        "framing_bytes": 0, "goodput_fraction": None,
        "overhead_ratio": None, "batch_saved_bytes": 0,
        "residual_bytes": None, "transport_bytes": 0, "failures": 0}},
        "amplification": {}}
    sample = fleet.FleetView([_target_with(wc)]).poll()
    rows = fleet.evaluate_slo({"min_goodput_fraction": 0.1}, sample)
    assert any(r["status"] == "fail" and r["subject"] == "mute|rx"
               for r in rows)


def test_load_slo_validates_cost_keys(tmp_path):
    p = tmp_path / "slo.json"
    p.write_text(json.dumps({"min_goodput_fraction": 1.5}))
    with pytest.raises(ValueError, match="unreachable"):
        fleet.load_slo(str(p))
    p.write_text(json.dumps({"max_overhead_ratio": "high"}))
    with pytest.raises(ValueError, match="number"):
        fleet.load_slo(str(p))
    p.write_text(json.dumps({"max_egress_bytes_per_peer": 1_000_000,
                             "min_goodput_fraction": 0.8}))
    slo = fleet.load_slo(str(p))
    assert slo["min_goodput_fraction"] == 0.8
