"""Device-path telemetry (ISSUE 5): recompile sentinel, backend-init
watchdog, chiplock metrics, perf-budget gate.

The sentinel's acceptance shape: a deliberately shape-UNSTABLE jit
site is counted trace-by-trace (and flagged over budget), while a
bucketed/shape-stable one stays silent after its first specialization.
The watchdog's: a stubbed slow init fires the deadline and the flight
bundle's manifest names the stage it was stuck in.  The gate's: the
checked-in snapshot passes against the checked-in budgets; a doctored
regression fails.
"""

import io
import json
import os
import time

import numpy as np
import pytest

import bench
from dat_replication_protocol_tpu.obs import device as obs_device
from dat_replication_protocol_tpu.obs import events as obs_events
from dat_replication_protocol_tpu.obs import flight as obs_flight
from dat_replication_protocol_tpu.obs import metrics as obs_metrics
from dat_replication_protocol_tpu.obs import perf as obs_perf
from dat_replication_protocol_tpu.obs.device import (
    BackendInitWatchdog,
    RecompileBudget,
    SENTINEL,
    jit_site,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUDGETS = os.path.join(REPO, "artifacts", "perf_budgets.json")
SNAPSHOT = os.path.join(REPO, "artifacts", "perf_snapshot_host.json")


# -- recompile sentinel -------------------------------------------------------


def test_sentinel_counts_shape_unstable_jit(obs_enabled):
    """The unbucketed-batch-size failure mode (ops/blake2b.py's
    bucketing comment): every distinct shape is a fresh trace, and the
    sentinel must count each one."""
    import jax

    f = jit_site("test.unstable", jax.jit(lambda x: x + 1))
    for n in range(1, 6):
        f(np.ones((n,), np.float32))
    snap = SENTINEL.snapshot()["test.unstable"]
    assert snap == {"calls": 5, "traces": 5}
    events = obs_events.EVENTS.events("device.jit.trace")
    assert len(events) == 5
    sigs = [e["fields"]["signature"] for e in events]
    assert sigs[0] == "(1,)float32" and sigs[-1] == "(5,)float32"
    assert obs_metrics.REGISTRY.counter("device.jit.traces").value == 5
    assert obs_metrics.REGISTRY.counter("device.jit.calls").value == 5


def test_sentinel_silent_for_bucketed_shapes(obs_enabled):
    """A bucketed site (one padded shape reused) traces once, then
    every later call is a cache hit — no further trace events."""
    import jax

    f = jit_site("test.bucketed", jax.jit(lambda x: x * 2))
    for _ in range(8):
        f(np.ones((16,), np.float32))
    snap = SENTINEL.snapshot()["test.bucketed"]
    assert snap == {"calls": 8, "traces": 1}
    assert len(obs_events.EVENTS.events("device.jit.trace")) == 1
    assert RecompileBudget(2).ok()


def test_sentinel_budget_flags_offender_once(obs_enabled):
    import jax

    f = jit_site("test.offender", jax.jit(lambda x: x + 1))
    for n in range(1, obs_device.DEFAULT_RECOMPILE_BUDGET + 4):
        f(np.ones((n,), np.float32))
    over = RecompileBudget(obs_device.DEFAULT_RECOMPILE_BUDGET).check()
    assert over and over[0]["site"] == "test.offender"
    assert over[0]["traces"] == obs_device.DEFAULT_RECOMPILE_BUDGET + 3
    # the breach event fires exactly once per site per process
    breaches = obs_events.EVENTS.events("device.jit.recompile_budget")
    assert len(breaches) == 1
    assert breaches[0]["fields"]["site"] == "test.offender"
    assert breaches[0]["fields"]["budget"] == \
        obs_device.DEFAULT_RECOMPILE_BUDGET


def test_sentinel_fallback_counter_without_cache_introspection(obs_enabled):
    """A callable with no ``_cache_size`` (custom engines, wrappers)
    rides the arg-signature fallback closure."""
    f = jit_site("test.fallback", lambda x, k=1: x)
    f(np.ones((2, 2)))
    f(np.ones((2, 2)))
    f(np.ones((4, 2)))
    f(np.ones((2, 2)), k=2)  # static kwarg change = new specialization
    assert SENTINEL.snapshot()["test.fallback"] == {"calls": 4, "traces": 3}


def test_sentinel_dark_while_gate_off():
    """Gate off: the wrapper is a pass-through — no stats, no events,
    no counters (the zero-telemetry contract)."""
    obs_metrics.disable()
    SENTINEL.reset_for_tests()
    calls = []
    f = jit_site("test.dark", lambda x: calls.append(x) or x)
    f(1)
    f(2)
    assert calls == [1, 2]  # the wrapped fn ran
    assert SENTINEL.snapshot() == {}


def test_sentinel_wrapper_delegates_jit_attributes(obs_enabled):
    import jax

    inner = jax.jit(lambda x: x + 1)
    f = jit_site("test.delegate", inner)
    assert f.__wrapped__ is inner
    # PjitFunction surface stays reachable through the wrapper
    assert callable(f.lower)


def test_sentinel_disabled_path_is_gate_bound():
    """Disabled-path budget (same coarse discipline as
    test_obs_metrics): the wrapper must cost about one gate check +
    one call — bound it at a generous absolute per-call budget."""
    obs_metrics.disable()
    f = jit_site("test.budget", lambda x: x)
    N = 100_000
    f(1)  # warm
    t0 = time.perf_counter()
    for _ in range(N):
        f(1)
    dt = time.perf_counter() - t0
    assert dt < N * 10e-6, f"disabled jit_site {dt / N * 1e9:.0f}ns/call"
    assert SENTINEL.snapshot().get("test.budget") is None


def test_repo_jit_entry_points_ride_the_sentinel(obs_enabled):
    """The wired sites: one real blake2b batch through the ops layer
    must show up in the sentinel snapshot and move the transfer
    counters."""
    from dat_replication_protocol_tpu.ops.blake2b import blake2b_batch

    digs = blake2b_batch([b"a" * 100, b"b" * 200])
    assert len(digs) == 2
    snap = SENTINEL.snapshot()
    assert "ops.blake2b.packed" in snap
    assert snap["ops.blake2b.packed"]["calls"] >= 1
    assert obs_metrics.REGISTRY.counter("device.h2d.bytes").value > 0
    assert obs_metrics.REGISTRY.counter("device.d2h.bytes").value >= 128


def test_sentinel_claims_trace_once_across_overlapping_threads(obs_enabled):
    """A cache-hit call overlapping another thread's trace must not be
    counted as a second trace: the claim happens under the stats lock
    against the cache high-water (first updater wins)."""
    import threading

    class FakeJit:
        """Jit-shaped: a shared cache counter, with call B parked
        inside the wrapped call while A's trace grows the cache."""

        def __init__(self):
            self.cache = 0
            self.b_inside = threading.Event()
            self.release_b = threading.Event()

        def _cache_size(self):
            return self.cache

        def __call__(self, x, who="a"):
            if who == "b":
                self.b_inside.set()
                self.release_b.wait(timeout=5)
                return x  # cache HIT: b compiles nothing
            self.cache += 1  # a's call traces
            return x

    fake = FakeJit()
    f = jit_site("test.overlap", fake)
    out = []
    tb = threading.Thread(target=lambda: out.append(f(1, who="b")))
    tb.start()
    assert fake.b_inside.wait(timeout=5)  # b sampled before=0, parked
    f(1, who="a")  # traces: cache 0 -> 1
    fake.release_b.set()  # b returns, sees now=1 > before=0 (stale)
    tb.join(timeout=5)
    snap = SENTINEL.snapshot()["test.overlap"]
    assert snap["calls"] == 2 and snap["traces"] == 1, snap


def test_sentinel_ignores_trace_time_invocations(obs_enabled):
    """A wrapped site called from INSIDE another jitted program runs
    once per OUTER trace, never per execution — counting it would
    report calls == traces for a healthy inner site (and charge the
    outer program's retraces to it)."""
    import jax

    inner = jit_site("test.inner", jax.jit(lambda x: x + 1))
    outer = jax.jit(lambda x: inner(x) * 2)
    for _ in range(3):
        outer(np.ones((4,), np.float32))  # one trace, two cached hits
    assert "test.inner" not in SENTINEL.snapshot()
    # direct (host-side) calls still count
    inner(np.ones((4,), np.float32))
    assert SENTINEL.snapshot()["test.inner"]["calls"] == 1


# -- engine-selection attribution --------------------------------------------


def test_note_engine_records_changes_only(obs_enabled):
    obs_device.note_engine("test.component", "pallas", items=4)
    obs_device.note_engine("test.component", "pallas", items=9)
    obs_device.note_engine("test.component", "native")
    sel = obs_events.EVENTS.events("device.engine.select")
    assert [e["fields"]["engine"] for e in sel] == ["pallas", "native"]


def test_note_engine_key_widens_the_memo(obs_enabled):
    """Per-bucket engine decisions dedup per (component, key): a mix
    straddling the pallas item floor must not flap the memo (ring
    churn), yet each bucket's choice is recorded once."""
    for _ in range(3):
        obs_device.note_engine("test.bucketed", "pallas", key=8)
        obs_device.note_engine("test.bucketed", "xla-scan", key=1)
    sel = obs_events.EVENTS.events("device.engine.select")
    assert [e["fields"]["engine"] for e in sel] == ["pallas", "xla-scan"]


# -- backend-init watchdog ----------------------------------------------------


def test_watchdog_fires_and_bundle_names_stuck_stage(tmp_path, obs_enabled):
    """A stubbed slow init: the deadline fires mid-stage and the
    flight bundle's manifest names the stage it was stuck in (the
    opaque round-5 87s hang, attributed)."""
    obs_flight.FLIGHT.arm(str(tmp_path))
    fired = []
    with BackendInitWatchdog(deadline_s=0.08,
                             on_timeout=fired.append) as wd:
        wd.stage("platform_probe")
        wd.stage("first_device_call")
        time.sleep(0.3)  # stuck "in" first_device_call
    assert wd.fired and fired and fired[0] is wd
    stuck = obs_events.EVENTS.events("backend.init.stuck")
    assert stuck and stuck[0]["fields"]["stage"] == "first_device_call"
    bundles = [d for d in os.listdir(tmp_path) if d.startswith("bundle-")]
    assert len(bundles) == 1 and "backend-init-stuck" in bundles[0]
    man = obs_flight.read_bundle(str(tmp_path / bundles[0]))["manifest"]
    assert man["extra"]["stage"] == "first_device_call"
    assert man["extra"]["elapsed_s"] >= 0.08
    assert [s["stage"] for s in man["extra"]["stages"]] == [
        "platform_probe", "first_device_call"]


def test_watchdog_clean_init_fires_nothing(tmp_path, obs_enabled):
    obs_flight.FLIGHT.arm(str(tmp_path))
    with BackendInitWatchdog(deadline_s=30.0) as wd:
        wd.stage("platform_probe")
        wd.stage("first_compile")
    assert not wd.fired
    assert not [d for d in os.listdir(tmp_path) if d.startswith("bundle-")]
    done = obs_events.EVENTS.events("backend.init.done")
    assert done and done[0]["fields"]["stuck"] is False
    assert obs_events.EVENTS.count("backend.init.stage") == 2
    # the whole init rides one span for the Chrome trace
    from dat_replication_protocol_tpu.obs import tracing as obs_tracing

    assert obs_tracing.SPANS.spans("backend.init")


def test_watchdog_timer_cancelled_after_clean_exit(obs_enabled):
    """No late fire: a watchdog that exited cleanly must not dump after
    its deadline passes."""
    with BackendInitWatchdog(deadline_s=0.05) as wd:
        wd.stage("platform_probe")
    time.sleep(0.12)
    assert not wd.fired
    assert not obs_events.EVENTS.events("backend.init.stuck")


# -- chiplock metrics (ISSUE 5 satellite) ------------------------------------


def test_chiplock_wait_histogram_and_counters(tmp_path, monkeypatch,
                                              obs_enabled):
    from dat_replication_protocol_tpu.utils import chiplock

    monkeypatch.setenv("DAT_CHIP_LOCK", str(tmp_path / "chip.lock"))
    with chiplock.chip_lock(max_wait=1.0) as lease:
        assert lease.held
    h = obs_metrics.REGISTRY.histogram("device.chiplock.wait")
    assert h.count == 1
    assert obs_metrics.REGISTRY.counter("device.chiplock.acquires").value == 1
    assert obs_metrics.REGISTRY.counter("device.chiplock.contended").value == 0


def test_chiplock_contention_counted(tmp_path, monkeypatch, obs_enabled):
    """A held lock (other fd, same file: flock excludes per open-file-
    description) makes the second acquirer wait — the contention
    counter and a nonzero wait observation must record it."""
    import fcntl

    from dat_replication_protocol_tpu.utils import chiplock

    lock = str(tmp_path / "chip.lock")
    monkeypatch.setenv("DAT_CHIP_LOCK", lock)
    fd = os.open(lock, os.O_CREAT | os.O_RDWR, 0o666)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        with chiplock.chip_lock(max_wait=0.2, poll_s=0.05) as lease:
            assert not lease.held  # ran lockless after max_wait
    finally:
        os.close(fd)
    assert obs_metrics.REGISTRY.counter(
        "device.chiplock.contended").value == 1
    assert obs_metrics.REGISTRY.counter(
        "device.chiplock.lockless").value == 1
    assert obs_metrics.REGISTRY.histogram("device.chiplock.wait").count == 1


# -- perf-budget gate ---------------------------------------------------------


def test_perf_check_passes_on_checked_in_snapshot():
    budgets = obs_perf.load_budgets(BUDGETS)
    with open(SNAPSHOT, encoding="utf-8") as f:
        snap = json.load(f)
    rows = obs_perf.check_snapshot(snap, budgets, host_only=True)
    fails = [r for r in rows if r["status"] == "fail"]
    assert not fails, fails
    # and the checks actually RAN (a gate that skips everything passes
    # vacuously)
    assert sum(r["status"] == "ok" for r in rows) >= 4


def test_perf_check_fails_on_doctored_regression():
    budgets = obs_perf.load_budgets(BUDGETS)
    with open(SNAPSHOT, encoding="utf-8") as f:
        snap = json.load(f)
    snap["configs"]["replay"]["value"] /= 1000.0  # the round-2 class
    rows = obs_perf.check_snapshot(snap, budgets, host_only=True)
    bad = obs_perf.find_first_failure(rows)
    assert bad is not None and bad["config"] == "replay"


def test_perf_check_lower_is_better_direction():
    budgets = {"configs": {"resume": {"group": "host", "checks": [
        {"field": "value", "direction": "lower",
         "reference": 0.5, "ratio": 0.05}]}}}
    ok = {"configs": {"resume": {"value": 0.2}}}
    slow = {"configs": {"resume": {"value": 50.0}}}  # > 0.5/0.05
    assert obs_perf.find_first_failure(
        obs_perf.check_snapshot(ok, budgets)) is None
    assert obs_perf.find_first_failure(
        obs_perf.check_snapshot(slow, budgets)) is not None


def test_perf_check_reduced_config_uses_loose_ratio():
    budgets = {"configs": {"hash": {"checks": [
        {"field": "value", "direction": "higher",
         "reference": 100.0, "ratio": 0.5, "reduced_ratio": 0.01}]}}}
    full = {"configs": {"hash": {"value": 10.0}}}          # < 50: fail
    reduced = {"configs": {"hash": {"value": 10.0,
                                    "reduced_config": True}}}  # > 1: ok
    assert obs_perf.find_first_failure(
        obs_perf.check_snapshot(full, budgets)) is not None
    assert obs_perf.find_first_failure(
        obs_perf.check_snapshot(reduced, budgets)) is None


def test_perf_check_malformed_ratio_fails_not_crashes():
    """A zero/negative/non-numeric ratio (reduced_ratio included) is a
    per-check FAIL row, never a ZeroDivisionError traceback."""
    for bad in (0, -1, "x"):
        budgets = {"configs": {"resume": {"checks": [
            {"field": "value", "direction": "lower",
             "reference": 0.5, "ratio": bad}]}}}
        rows = obs_perf.check_snapshot(
            {"configs": {"resume": {"value": 0.1}}}, budgets)
        assert rows[0]["status"] == "fail" and "malformed" in rows[0]["detail"]
    budgets = {"configs": {"hash": {"checks": [
        {"field": "value", "direction": "higher",
         "reference": 1.0, "ratio": 0.5, "reduced_ratio": 0}]}}}
    rows = obs_perf.check_snapshot(
        {"configs": {"hash": {"value": 2.0, "reduced_config": True}}},
        budgets)
    assert rows[0]["status"] == "fail"


def test_perf_check_entry_without_checks_fails_not_passes():
    """A budgeted config whose entry has no (or a mistyped) checks list
    must fail loudly, not pass vacuously."""
    for entry in ({}, {"checks": []}, {"check": [{"field": "value"}]}):
        budgets = {"configs": {"hash": dict(entry)}}
        rows = obs_perf.check_snapshot(
            {"configs": {"hash": {"value": 2.0}}}, budgets)
        assert rows[0]["status"] == "fail"
        assert "no evaluable checks" in rows[0]["detail"]


def test_perf_check_missing_and_errored_configs_fail_unless_optional():
    budgets = {"configs": {
        "hash": {"checks": [{"field": "value", "direction": "higher",
                             "reference": 1.0, "ratio": 0.5}]},
        "cdc": {"optional": True,
                "checks": [{"field": "value", "direction": "higher",
                            "reference": 1.0, "ratio": 0.5}]},
    }}
    snap = {"configs": {"hash": {"error": "boom"}}}
    rows = obs_perf.check_snapshot(snap, budgets)
    by = {r["config"]: r["status"] for r in rows}
    assert by == {"hash": "fail", "cdc": "skip"}


def test_perf_check_cli_exit_codes(tmp_path):
    from dat_replication_protocol_tpu.obs.__main__ import main

    out = io.StringIO()
    rc = obs_perf.run_check(SNAPSHOT, BUDGETS, host_only=True, out=out)
    assert rc == 0 and "within budget" in out.getvalue()
    doctored = tmp_path / "bad.json"
    with open(SNAPSHOT, encoding="utf-8") as f:
        snap = json.load(f)
    snap["configs"]["roundtrip"]["value"] = 1.0
    doctored.write_text(json.dumps(snap))
    assert main(["perf-check", str(doctored), "--budgets", BUDGETS,
                 "--host-only"]) == 1
    assert main(["perf-check", SNAPSHOT, "--budgets", BUDGETS,
                 "--host-only"]) == 0


def test_perf_check_parses_artifact_with_log_noise(tmp_path):
    """Driver logs wrap the artifact line in stderr noise; the parser
    must find the one JSON object line."""
    noisy = tmp_path / "noisy.json"
    with open(SNAPSHOT, encoding="utf-8") as f:
        line = json.dumps(json.load(f))
    noisy.write_text("bench: starting\n" + line + "\nbench: done\n")
    assert obs_perf.run_check(str(noisy), BUDGETS, host_only=True,
                              out=io.StringIO()) == 0


def test_perf_check_prefers_the_configs_object_over_earlier_json(tmp_path):
    """A log that also interleaves OTHER JSON lines (--stats-fd
    periodic snapshots) must still evaluate the bench artifact — the
    last object carrying a 'configs' table, not the first '{' line."""
    noisy = tmp_path / "interleaved.json"
    with open(SNAPSHOT, encoding="utf-8") as f:
        artifact = json.dumps(json.load(f))
    stats_line = json.dumps({"ts": 1.0, "metrics": {"counters": {}}})
    noisy.write_text(stats_line + "\nnoise\n" + artifact + "\ntrailer\n")
    assert obs_perf.run_check(str(noisy), BUDGETS, host_only=True,
                              out=io.StringIO()) == 0


# -- tier-1 gate wiring: the gate exercised end-to-end on a real (tiny)
# host-group bench run (ISSUE 5 satellite: CPU-safe, generous budgets)


def _live_bench_env() -> dict:
    env = dict(os.environ)
    env.update(BENCH_CONFIGS="1,2,6,7,8,9,10,11,12,13,14",
               BENCH_ROUNDTRIPS="50",
               BENCH_DECODE_ROWS="4000", BENCH_REPLAY_ROWS="4000",
               BENCH_RESUME_ROWS="300", BENCH_RESUME_REPS="3",
               BENCH_WIRE_BATCH_ROWS="12288", BENCH_FUSED_MIB="64",
               BENCH_HUB_SESSIONS="6", BENCH_HUB_ROWS="1024",
               BENCH_HUB_BLOB_KIB="128", BENCH_FANOUT_ROWS="1024",
               BENCH_FANOUT_BLOB_KIB="128", BENCH_FANOUT_PEERS="1,8",
               BENCH_FANOUT_STALL_S="0.3", BENCH_RECONCILE_N="6000",
               BENCH_RECONCILE_KS="10,100", BENCH_SNAPSHOT_MIB="4",
               BENCH_SNAPSHOT_JOINERS="4", BENCH_PUMP_MIB="16",
               BENCH_PUMP_SESSIONS="1,4", BENCH_PUMP_REPS="2",
               BENCH_GOSSIP_N="4,8", BENCH_GOSSIP_RECORDS="32",
               BENCH_GOSSIP_DIVERGENCE="8",
               BENCH_DEADLINE="300")
    return env


def _run_quick_bench(env: dict, timeout: int = 280) -> dict:
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--quick",
         "--metrics"],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    return obs_perf._parse_snapshot(r.stdout, "live-bench-stdout")


def _failing_configs(snapshot: dict) -> list:
    budgets = obs_perf.load_budgets(BUDGETS)
    rows = obs_perf.check_snapshot(snapshot, budgets, host_only=True)
    return sorted({r["config"] for r in rows if r["status"] == "fail"})


def test_perf_check_host_only_on_live_quick_bench(tmp_path, monkeypatch):
    snapshot = _run_quick_bench(_live_bench_env())
    failing = _failing_configs(snapshot)
    if failing:
        # one-retry-with-margin rule (ISSUE 15 satellite): a
        # budget-floor miss on the shared tier-1 run can be CI LOAD,
        # not a regression — the whole suite plus this very bench were
        # competing for the 2-core box.  Re-run EXACTLY the failing
        # configs once, in isolation (their own process, nothing else
        # running), and gate on that result.  A true regression fails
        # both runs; only the isolated verdict counts, and only one
        # retry is allowed — "any failure is a real regression" stays
        # true, with the load-flake class carved out mechanically.
        keys = [k for k, (nm, _fn) in bench.BENCHES.items()
                if nm in failing]
        assert keys, f"unrunnable failing configs: {failing}"
        env = _live_bench_env()
        env["BENCH_CONFIGS"] = ",".join(keys)
        rerun = _run_quick_bench(env)
        for name in failing:
            assert name in rerun.get("configs", {}), (
                f"isolated re-run produced no result for {name}")
            snapshot["configs"][name] = rerun["configs"][name]
        still = _failing_configs(snapshot)
        assert not still, (
            f"configs {still} missed their budget floor twice — once "
            f"under load and once in isolation: a real regression")


# -- bench backend_error structure (ISSUE 5 satellite) ------------------------


def test_probe_failure_carries_stage_and_elapsed():
    stdout = "STAGE platform_probe\nSTAGE first_device_call\n"
    err = bench._probe_failure("backend init hung (> 87s)", stdout, 87.3)
    assert err == {"message": "backend init hung (> 87s)",
                   "stage": "first_device_call", "elapsed_s": 87.3}
    assert bench._probe_stage("") is None
    assert bench._probe_stage(None) is None


def test_probe_backend_reports_stage_on_real_failure():
    """A probe forced onto a nonexistent platform must fail (fast) with
    a structured record whose stage is from the real ladder."""
    backend, err = bench._probe_backend("no_such_platform", timeout=120)
    assert backend is None
    assert isinstance(err, dict)
    assert set(err) >= {"message", "stage", "elapsed_s"}
    assert err["stage"] in (None,) + obs_device.INIT_STAGES


def test_emit_carries_structured_backend_error(monkeypatch, capsys):
    monkeypatch.setattr(bench, "_emitted", False)
    monkeypatch.setitem(bench._state, "configs", {})
    monkeypatch.setitem(
        bench._state, "backend_error",
        {"message": "backend init hung (> 87s)",
         "stage": "first_device_call", "elapsed_s": 87.0})
    bench._emit()
    out = json.loads(capsys.readouterr().out)
    assert out["backend_error"]["stage"] == "first_device_call"
    assert out["backend_error"]["elapsed_s"] == 87.0


def test_digest_pipeline_counts_stream_bytes(obs_enabled):
    """submit_stream carries a blob-heavy session's dominant volume;
    device.submit.bytes must account it (catalog contract)."""
    from dat_replication_protocol_tpu.backend.tpu_backend import (
        DigestPipeline, _HostStream,
    )

    pipe = DigestPipeline(hash_batch=lambda ps: [b"\0" * 32 for _ in ps])
    s = _HostStream()
    s.update(b"x" * 1000)
    got = []
    pipe.submit_stream(s, got.append)
    pipe.submit(b"y" * 10, got.append)
    pipe.flush()
    assert len(got) == 2
    assert obs_metrics.REGISTRY.counter("device.submit.bytes").value == 1010
    assert obs_metrics.REGISTRY.counter("device.submit.items").value == 2


def test_bench_trace_export_resets_engine_memo(tmp_path, obs_enabled):
    """The per-config ring clear must also reset the engine-select
    memo, or every config after the first loses its attribution."""
    obs_device.note_engine("test.memo", "xla-scan")
    bench._export_config_trace("memo_probe", str(tmp_path))
    assert obs_events.EVENTS.events("device.engine.select") == []
    obs_device.note_engine("test.memo", "xla-scan")  # same engine again
    sel = obs_events.EVENTS.events("device.engine.select")
    assert len(sel) == 1  # re-emitted into the fresh capture


def test_device_telemetry_subset_filters_prefixes(obs_enabled):
    obs_metrics.REGISTRY.counter("device.h2d.bytes").inc(7)
    obs_metrics.REGISTRY.counter("decoder.bytes").inc(9)
    obs_metrics.REGISTRY.histogram("device.chiplock.wait").observe(0.5)
    obs_metrics.REGISTRY.histogram("decoder.dispatch.seconds").observe(0.1)
    sub = bench._device_telemetry_subset()
    assert sub["counters"].get("device.h2d.bytes") == 7
    assert "decoder.bytes" not in sub["counters"]
    # the one device-path histogram rides the subset too
    assert sub["histograms"]["device.chiplock.wait"]["count"] == 1
    assert "decoder.dispatch.seconds" not in sub["histograms"]
