"""Quantitative streaming discipline: bounded memory, concurrent soak.

The reference's core memory property is O(chunk), never O(blob)
(reference: README.md:73); these tests measure it rather than assume
it — encoder queue occupancy against its high-water mark under a slow
consumer, and a many-session concurrent soak over real sockets.
"""

import threading

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.session.transport import (
    session_over_socketpair,
)

CHUNK = 16 * 1024


def test_encoder_queue_bounded_by_high_water_under_slow_consumer():
    hw = 64 * 1024
    enc = protocol.encode(high_water=hw)
    dec = protocol.decode()
    total = 4 << 20  # 256x the high-water mark
    received = [0]
    gate = threading.Semaphore(0)

    def on_blob(b, done):
        def on_data(piece):
            received[0] += len(piece)
            gate.acquire()  # consumer drains only when released

        b.on_data(on_data)
        b.on_end(done)

    dec.blob(on_blob)
    peak = [0]

    def producer():
        ws = enc.blob(total)
        sent = 0
        while sent < total:
            n = min(CHUNK, total - sent)
            ws.write(b"\xcd" * n)
            sent += n
            peak[0] = max(peak[0], enc.buffered_bytes)
            if not enc.writable():
                # the app-visible stall: honor it like a well-behaved
                # producer (drain callback would be the event-driven way)
                while not enc.writable() and not enc.destroyed:
                    gate.release()  # let the consumer eat
        ws.end()
        enc.finalize()

    sess = session_over_socketpair(enc, dec, chunk_size=CHUNK,
                                   sndbuf=32 * 1024)
    t = threading.Thread(target=producer, daemon=True)
    t.start()
    for _ in range(10 * total // CHUNK):
        gate.release()
    t.join(30)
    sess.wait(30)
    assert received[0] == total
    # a producer that respects writable() keeps queue occupancy within
    # one write of the mark — O(high_water), never O(blob)
    assert peak[0] <= hw + CHUNK, f"peak {peak[0]} vs high-water {hw}"


def test_concurrent_sessions_soak():
    n_sessions = 12
    payload = b"\xee" * 100_000
    results = [None] * n_sessions
    errors = []

    def one(i):
        try:
            enc, dec = protocol.encode(), protocol.decode()
            got = {}
            dec.change(
                lambda c, done: (got.setdefault("keys", []).append(c.key),
                                 done())
            )
            dec.blob(
                lambda b, done: b.collect(
                    lambda d: (got.setdefault("blobs", []).append(d), done())
                )
            )
            dec.finalize(lambda done: done())
            sess = session_over_socketpair(enc, dec, sndbuf=16 * 1024)
            for k in range(5):
                enc.change({"key": f"s{i}-{k}", "change": k, "from": k,
                            "to": k + 1})
            ws = enc.blob(len(payload))
            for off in range(0, len(payload), 8192):
                ws.write(payload[off:off + 8192])
            ws.end()
            enc.finalize()
            sess.wait(30)
            assert got["keys"] == [f"s{i}-{k}" for k in range(5)]
            assert got["blobs"] == [payload]
            assert enc.bytes == dec.bytes
            results[i] = True
        except Exception as e:  # surface per-session failures
            errors.append((i, e))

    threads = [threading.Thread(target=one, args=(i,)) for i in
               range(n_sessions)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errors, errors
    assert all(results), results
