"""Event-loop readiness certifier (ISSUE 16): fixture suites for the
may-block summary lattice, blocking-reachability, callback-escape, the
certificate renderer, and the structured CLI surfaces added alongside
(--format json|sarif, --write-artifacts).

Fixture doctrine (same as test_datlint.py): each bad fixture is a
minimal re-creation of the real pattern the pass certifies against —
if a classification flips on it, the certifier has lost the property
the item-2 rewrite diffs.
"""

import json
import textwrap

from dat_replication_protocol_tpu.analysis import run_paths
from dat_replication_protocol_tpu.analysis.__main__ import \
    main as datlint_main
from dat_replication_protocol_tpu.analysis.concurrency import (
    BlockingReachability,
    CallbackEscape,
    ReadinessIndex,
    render_event_loop_surface,
)
from dat_replication_protocol_tpu.analysis.engine import Project

READY_RULES = (BlockingReachability(), CallbackEscape())


def _write(tmp_path, *files):
    for name, source in files:
        (tmp_path / name).write_text(textwrap.dedent(source))
    return tmp_path


def _lint(tmp_path, *files, rules=READY_RULES):
    _write(tmp_path, *files)
    return run_paths([tmp_path], rules=rules)


def _index(tmp_path, *files):
    _write(tmp_path, *files)
    return ReadinessIndex.get(Project.from_paths([tmp_path]))


def _summary(idx, suffix):
    keys = [k for k in idx.fns if k.endswith(suffix)]
    assert keys, f"no function key ends with {suffix!r}: {sorted(idx.fns)}"
    return idx.fns[keys[0]].summary


# -- the summary lattice ------------------------------------------------------

def test_timeout_wait_is_bounded_bare_wait_is_not(tmp_path):
    idx = _index(tmp_path, ("w.py", '''
        import threading

        class Loop:
            def __init__(self):
                self._ev = threading.Event()

            def carries(self):
                self._ev.wait(0.5)

            def carries_kw(self):
                self._ev.wait(timeout=2.0)

            def bare(self):
                self._ev.wait()

            def explicit_none(self):
                self._ev.wait(timeout=None)
    '''))
    assert _summary(idx, "::Loop.carries") == "bounded-blocking"
    assert _summary(idx, "::Loop.carries_kw") == "bounded-blocking"
    assert _summary(idx, "::Loop.bare") == "unbounded-blocking"
    assert _summary(idx, "::Loop.explicit_none") == "unbounded-blocking"


def test_sleep_join_and_acquire_boundedness(tmp_path):
    idx = _index(tmp_path, ("j.py", '''
        import time

        def naps():
            time.sleep(0.01)

        def joins_bounded(worker):
            worker.join(timeout=5)

        def joins_forever(worker):
            worker.join()

        def string_join_is_not_a_wait(parts):
            return ",".join(parts)

        class L:
            def try_lock(self):
                return self._lock.acquire(blocking=False)

            def takes_lock(self):
                self._lock.acquire()
    '''))
    assert _summary(idx, "::naps") == "bounded-blocking"
    assert _summary(idx, "::joins_bounded") == "bounded-blocking"
    assert _summary(idx, "::joins_forever") == "unbounded-blocking"
    assert _summary(idx, "::string_join_is_not_a_wait") == "nonblocking"
    assert _summary(idx, "::L.try_lock") == "bounded-blocking"
    assert _summary(idx, "::L.takes_lock") == "unbounded-blocking"


def test_summary_propagates_through_calls(tmp_path):
    idx = _index(tmp_path, ("p.py", '''
        def leaf(sock):
            sock.recv(4096)

        def middle(sock):
            leaf(sock)

        def top(sock):
            middle(sock)
    '''))
    for fn in ("::leaf", "::middle", "::top"):
        assert _summary(idx, fn) == "unbounded-blocking"


def test_recursion_cycle_terminates_and_stays_sound(tmp_path):
    # ping <-> pong call each other forever; pong also reaches a bare
    # recv.  The fixpoint must terminate (monotone on a finite
    # lattice) and BOTH cycle members must inherit the unbounded site.
    idx = _index(tmp_path, ("cycle.py", '''
        def blocker(sock):
            sock.recv(1)

        def ping(sock, n):
            if n:
                pong(sock, n - 1)

        def pong(sock, n):
            ping(sock, n)
            blocker(sock)
    '''))
    assert _summary(idx, "::ping") == "unbounded-blocking"
    assert _summary(idx, "::pong") == "unbounded-blocking"


def test_thread_spawn_does_not_raise_spawner_summary(tmp_path):
    # Thread(target=self._run) with a bound method: starting a thread
    # is nonblocking, but the TARGET's classification must resolve and
    # surface as a spawn edge
    idx = _index(tmp_path, ("t.py", '''
        import threading

        class Boss:
            def start(self):
                t = threading.Thread(target=self._run, daemon=True)
                t.start()

            def _run(self):
                self.sock.recv(1)
    '''))
    assert _summary(idx, "::Boss.start") == "nonblocking"
    assert _summary(idx, "::Boss._run") == "unbounded-blocking"
    start = [rf for k, rf in idx.fns.items() if k.endswith("::Boss.start")]
    spawns = start[0].spawns
    assert len(spawns) == 1
    assert spawns[0].target is not None
    assert spawns[0].target.endswith("::Boss._run")


def test_lambda_stored_in_dict_links_to_dynamic_call(tmp_path):
    # the callback-escape edge case from the issue: the blocking call
    # hides behind a lambda stored in a dict, invoked dynamically
    idx = _index(tmp_path, ("d.py", '''
        class Srv:
            def __init__(self):
                self._handlers = {}
                self._handlers["x"] = lambda: self.sock.recv(1)

            def _dispatch_loop(self):
                self._handlers["x"]()
    '''))
    assert _summary(idx, "::Srv._dispatch_loop") == "unbounded-blocking"


def test_dict_literal_of_callables_links_too(tmp_path):
    idx = _index(tmp_path, ("dl.py", '''
        class Srv:
            def __init__(self):
                self._handlers = {"x": self._on_x}

            def _on_x(self):
                self.sock.recv(1)

            def _dispatch_loop(self):
                self._handlers["x"]()
    '''))
    assert _summary(idx, "::Srv._dispatch_loop") == "unbounded-blocking"


# -- blocking-reachability ----------------------------------------------------

def test_unbounded_site_reachable_from_dispatch_loop_fires(tmp_path):
    findings = _lint(tmp_path, ("srv.py", '''
        class Srv:
            def _dispatch_loop(self):
                self._pump()

            def _pump(self):
                self.sock.recv(4096)
    '''))
    assert [f.rule for f in findings] == ["blocking-reachability"]
    assert findings[0].line == 7
    # the evidence chain names both hops with file:line
    chain = findings[0].chains[0]
    assert any("_dispatch_loop" in step for step in chain)
    assert any(":7" in step and "recv" in step for step in chain)


def test_bounded_dispatch_loop_is_clean(tmp_path):
    findings = _lint(tmp_path, ("ok.py", '''
        import time

        class Srv:
            def _dispatch_loop(self):
                self._work.wait(0.25)
                time.sleep(0.002)
                if self._lock.acquire(blocking=False):
                    pass
    '''))
    assert findings == []


def test_allow_blocking_reachable_marker_silences(tmp_path):
    findings = _lint(tmp_path, ("allowed.py", '''
        class Srv:
            def _dispatch_loop(self):
                # fd is nonblocking here by construction (fixture).
                # datlint: allow-blocking-reachable(socket)
                self.sock.recv(4096)
    '''))
    assert findings == []


def test_blocking_outside_any_dispatcher_is_not_a_finding(tmp_path):
    # the rule certifies dispatch loops, not the whole program: a
    # session thread may block by contract
    findings = _lint(tmp_path, ("free.py", '''
        def session_thread(sock):
            sock.recv(4096)
    '''))
    assert findings == []


# -- callback-escape ----------------------------------------------------------

def test_user_callback_on_dispatcher_thread_fires(tmp_path):
    findings = _lint(tmp_path, ("cb.py", '''
        class Hub:
            def _dispatch_loop(self):
                self.on_done(3)
    '''))
    assert [f.rule for f in findings] == ["callback-escape"]
    assert "on_done" in findings[0].message


def test_allow_callback_escape_marker_silences(tmp_path):
    findings = _lint(tmp_path, ("cba.py", '''
        class Hub:
            def _dispatch_loop(self):
                # audited: fixture sink contract.
                # datlint: allow-callback-escape
                self.on_done(3)
    '''))
    assert findings == []


def test_callback_on_session_thread_is_not_an_escape(tmp_path):
    findings = _lint(tmp_path, ("sess.py", '''
        class Hub:
            def deliver(self):
                self.on_done(3)
    '''))
    assert findings == []


# -- the certificate ----------------------------------------------------------

def test_certificate_is_deterministic_and_byte_stable(tmp_path):
    files = (("srv.py", '''
        import threading

        class Srv:
            def __init__(self):
                self._work = threading.Event()

            def _dispatch_loop(self):
                self._work.wait(0.5)
                self._emit()

            def _emit(self):
                self.sock.sendall(b"x")
    '''),)
    _write(tmp_path, *files)
    docs = []
    for _ in range(2):
        # a FRESH project per render: memoized indices must not be the
        # only reason the bytes agree
        idx = ReadinessIndex.get(Project.from_paths([tmp_path]))
        docs.append(json.dumps(render_event_loop_surface(idx),
                               indent=2, sort_keys=True))
    assert docs[0] == docs[1]
    doc = json.loads(docs[0])
    assert doc["levels"] == ["nonblocking", "bounded-blocking",
                             "unbounded-blocking"]
    # the fixture tree has none of the real entry points: every named
    # spec must be reported missing, never silently dropped
    missing = {m["entry"] for m in doc["missing_entry_points"]}
    assert "hub-dispatch" in missing and "sidecar-session" in missing
    # the fixture dispatcher still certifies (by name pattern)
    entries = {e["entry"]: e for e in doc["entry_points"]}
    assert "Srv._dispatch_loop" in entries
    e = entries["Srv._dispatch_loop"]
    assert e["enforced"] is True
    assert e["classification"] == "unbounded-blocking"
    assert e["certified"] is False
    assert e["unbounded"][0]["call"] == "self.sock.sendall(...)"
    assert e["unbounded"][0]["chain"]  # file:line evidence present


def test_checked_in_certificate_shape(tmp_path):
    # structural invariants every consumer (ROADMAP item 2 diffing,
    # the tier-1 byte-match test) relies on
    _write(tmp_path, ("loop.py", '''
        class S:
            def _dispatch_loop(self):
                self._q.wait(0.1)
    '''))
    doc = render_event_loop_surface(
        ReadinessIndex.get(Project.from_paths([tmp_path])))
    assert set(doc) == {"version", "generator", "levels", "summary",
                        "entry_points", "missing_entry_points",
                        "unbounded_functions"}
    assert doc["version"] == 1
    counts = doc["summary"]
    assert counts["functions"] == (counts["nonblocking"]
                                   + counts["bounded-blocking"]
                                   + counts["unbounded-blocking"])


# -- CLI: --format json|sarif, --write-artifacts ------------------------------

BAD_TREE = ('''
    class Srv:
        def _dispatch_loop(self):
            self.sock.recv(4096)
''')


def test_format_json_round_trips_findings(tmp_path, capsys):
    _write(tmp_path, ("srv.py", BAD_TREE))
    rc = datlint_main(["--format", "json", "--rule",
                       "blocking-reachability", str(tmp_path)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    expected = [f.to_json() for f in run_paths(
        [tmp_path], rules=(BlockingReachability(),))]
    assert doc["findings"] == expected
    f = doc["findings"][0]
    assert set(f) == {"rule", "path", "line", "message", "chains"}
    assert f["rule"] == "blocking-reachability"
    assert f["chains"][0]  # evidence chain survives the round trip


def test_json_flag_is_an_alias_for_format_json(tmp_path, capsys):
    _write(tmp_path, ("srv.py", BAD_TREE))
    datlint_main(["--format", "json", "--rule", "blocking-reachability",
                  str(tmp_path)])
    via_format = capsys.readouterr().out
    datlint_main(["--json", "--rule", "blocking-reachability",
                  str(tmp_path)])
    assert capsys.readouterr().out == via_format


def test_json_flag_contradicting_format_is_a_usage_error(tmp_path):
    _write(tmp_path, ("ok.py", "X = 1\n"))
    assert datlint_main(["--json", "--format", "sarif",
                         str(tmp_path)]) == 2


def test_format_sarif_structure(tmp_path, capsys):
    _write(tmp_path, ("srv.py", BAD_TREE))
    rc = datlint_main(["--format", "sarif", "--rule",
                       "blocking-reachability", str(tmp_path)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "datlint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert rule_ids == {"blocking-reachability"}
    (res,) = run["results"]
    findings = run_paths([tmp_path], rules=(BlockingReachability(),))
    loc = res["locations"][0]["physicalLocation"]
    assert res["ruleId"] == findings[0].rule
    assert loc["artifactLocation"]["uri"] == findings[0].path
    assert loc["region"]["startLine"] == findings[0].line
    assert res["properties"]["chains"] == [list(c)
                                           for c in findings[0].chains]


def test_sarif_clean_tree_exits_zero_with_no_results(tmp_path, capsys):
    _write(tmp_path, ("ok.py", "X = 1\n"))
    rc = datlint_main(["--format", "sarif", "--rule",
                       "blocking-reachability", str(tmp_path)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["runs"][0]["results"] == []


def test_write_artifacts_regenerates_both_byte_stably(tmp_path, capsys):
    src = tmp_path / "tree"
    src.mkdir()
    (src / "loop.py").write_text(textwrap.dedent('''
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._work = threading.Event()

            def _dispatch_loop(self):
                with self._lock:
                    pass
                self._work.wait(0.1)
    '''))
    outs = []
    for name in ("a", "b"):
        out = tmp_path / name
        rc = datlint_main(["--write-artifacts", str(out), str(src)])
        capsys.readouterr()
        assert rc == 0
        assert (out / "lock_graph.json").exists()
        assert (out / "event_loop_surface.json").exists()
        outs.append(out)
    for fname in ("lock_graph.json", "event_loop_surface.json"):
        a = (outs[0] / fname).read_bytes()
        b = (outs[1] / fname).read_bytes()
        assert a == b, f"{fname} is not byte-stable across regeneration"
        assert a.endswith(b"\n")
