"""Fault-injection conformance sweep: the session survives chaos.

The conformance scenarios (test_session_conformance.py — changes, blobs,
interleaved corked blobs, changes parked behind blobs) run as ONE
session wire through the deterministic fault injector
(session/faults.py) and the resumable reconnect driver
(session/reconnect.py).  The contract under test (ISSUE 2 acceptance):
for every seed, an injected disconnect-class fault (drop / truncation /
stall / pathological re-segmentation) ends in either

* **byte-identical decoded output after resume** — same events, same
  order, same bytes, no duplicates, no gaps; or
* **exactly one structured ProtocolError** with frame/byte context;

and NEVER a hang: each case runs under a hard watchdog timeout.

The tier-1 subset sweeps seeds 0..19; the ``slow``-marked soak covers
200 seeds.  Corruption-class faults (byte flips) get targeted tests —
a flipped header must ERROR (not resume), and the error must carry
context.
"""

from __future__ import annotations

import threading

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.session.faults import (
    FaultPlan,
    FaultyReader,
    TransportFault,
    bytes_reader,
)
from dat_replication_protocol_tpu.session.reconnect import (
    BackoffPolicy,
    run_resumable,
)
from dat_replication_protocol_tpu.session.resume import WireJournal
from dat_replication_protocol_tpu.wire.framing import ProtocolError

HARD_TIMEOUT = 30.0  # per-case watchdog: "never a hang", enforced


def _build_wire() -> bytes:
    """One session covering every conformance scenario: a bulk change
    run (the native-indexed path), two interleaved corked blobs, a
    change parked behind an open blob, a multi-KiB blob (mid-payload
    fault territory), and trailing changes."""
    e = protocol.encode()
    j = WireJournal()
    e.attach_journal(j)
    for i in range(24):  # >= 16: exercises the bulk fast loop
        e.change({"key": f"bulk-{i}", "change": i, "from": i, "to": i + 1,
                  "value": b"v%03d" % i})
    b1 = e.blob(11)
    b2 = e.blob(11)
    b1.write(b"hello ")
    b2.write(b"HELLO ")
    b1.write(b"world")
    b2.write(b"WORLD")
    b1.end()
    b2.end()
    big = e.blob(3000)
    big.write(b"x" * 1700)
    e.change({"key": "parked", "change": 99, "from": 0, "to": 1,
              "value": b"after-blob"})
    big.end(b"y" * 1300)
    for i in range(8):
        e.change({"key": f"tail-{i}", "change": i, "from": i, "to": i + 1})
    e.finalize()
    while e.read(4096) is not None:
        pass
    return j.read_from(0)


_WIRE = _build_wire()


def _fresh_decoder(backend: str = "host"):
    """Decoder + its event sink; events capture order, keys, and bytes."""
    dec = protocol.decode(backend=backend)
    events: list = []
    dec.change(lambda c, done: (
        events.append(("change", c.key, c.value)), done()))
    dec.blob(lambda b, done: b.collect(
        lambda data: (events.append(("blob", data)), done())))
    if backend == "tpu":
        dec.on_digest(lambda kind, seq, d: events.append(("digest", kind, seq, d)))
    return dec, events


def _expected(backend: str = "host"):
    dec, events = _fresh_decoder(backend)
    for off in range(0, len(_WIRE), 777):
        dec.write(_WIRE[off:off + 777])
    dec.end()
    assert dec.finished
    return events


_EXPECTED = _expected()


def _with_watchdog(fn):
    """Run ``fn`` on a worker thread under the hard timeout; re-raise its
    outcome here.  A case that neither returns nor raises is a HANG —
    the exact failure class this suite exists to exclude."""
    box: dict = {}

    def run():
        try:
            box["ret"] = fn()
        except BaseException as e:  # noqa: BLE001 — relayed to the test
            box["err"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(HARD_TIMEOUT)
    assert not t.is_alive(), f"HANG: case still running after {HARD_TIMEOUT}s"
    if "err" in box:
        raise box["err"]
    return box["ret"]


def _run_seed(seed: int, backend: str = "host"):
    dec, events = _fresh_decoder(backend)

    def source(ckpt, failures):
        remaining = len(_WIRE) - ckpt.wire_offset
        plan = FaultPlan.for_sweep(seed, remaining, attempt=failures)
        return FaultyReader(bytes_reader(_WIRE[ckpt.wire_offset:]), plan)

    def drive():
        return run_resumable(
            source, dec,
            BackoffPolicy(base=0.0005, cap=0.005, max_retries=8, seed=seed),
            chunk_size=1024,
            expected_total=len(_WIRE),
            stall_timeout=HARD_TIMEOUT / 2,
        )

    try:
        stats = _with_watchdog(drive)
    except ProtocolError as e:
        # the error arm: exactly one structured error, with context
        assert e.offset is not None, f"unstructured ProtocolError: {e}"
        return None, None
    return stats, events


# -- tier-1 subset: 20 seeds, disconnect-class faults -----------------------

@pytest.mark.parametrize("seed", range(20))
def test_sweep_resumes_byte_identical(seed):
    stats, events = _run_seed(seed)
    # disconnect-class faults are absorbable by design: every seed must
    # converge (the plan generator goes clean after attempt 1), and the
    # decoded session must be byte-identical — no duplicate deliveries,
    # no gaps, no reordering across however many resumes happened
    assert stats is not None, "disconnect-class fault must resume, not error"
    assert events == _EXPECTED
    assert stats["reconnects"] == len(stats["faults"])


@pytest.mark.parametrize("seed", [3, 11])
def test_sweep_tpu_backend_digest_state_survives_resume(seed):
    expected = _expected(backend="tpu")
    stats, events = _run_seed(seed, backend="tpu")
    assert stats is not None
    # digests included: every (kind, seq) exactly once, values identical
    # to the unfaulted run — the checkpoint's digest counters mean a
    # resume neither re-hashes delivered frames nor skips sequence ids
    assert events == expected


# -- fused + double-buffered digest pipeline under faults (ISSUE 7) ---------

@pytest.mark.parametrize("seed", [2, 7, 13])
def test_sweep_fused_pipeline_digests_exactly_once(seed, monkeypatch):
    """Mid-blob/mid-run faults through the DONATED, double-buffered
    digest pipeline: the decoder's pipeline runs the jitted batch engine
    with donated input buffers and two batches in flight across the
    fault.  Digests must arrive exactly once per (kind, seq) with values
    identical to the unfaulted run — a donated buffer whose HBM was
    recycled mid-resume must never leak a stale block into the next
    dispatch's hashes."""
    import warnings

    from dat_replication_protocol_tpu.backend.tpu_backend import (
        DigestPipeline,
    )

    monkeypatch.setenv("DAT_DEVICE_HASH", "1")  # the jitted batch engine
    monkeypatch.setenv("DAT_DONATE", "1")       # donated staging buffers
    warnings.simplefilter("ignore")  # CPU jax warns per ignored donation

    def fresh():
        # small batch + inflight bounds: several batches genuinely in
        # flight while the fault machinery stalls/truncates/resumes
        dec = protocol.decode(
            backend="tpu",
            pipeline=DigestPipeline(max_batch=4, max_inflight=2),
        )
        events: list = []
        dec.change(lambda c, done: (
            events.append(("change", c.key, c.value)), done()))
        dec.blob(lambda b, done: b.collect(
            lambda data: (events.append(("blob", data)), done())))
        dec.on_digest(
            lambda kind, s, d: events.append(("digest", kind, s, d)))
        return dec, events

    exp_dec, expected = fresh()
    for off in range(0, len(_WIRE), 777):
        exp_dec.write(_WIRE[off:off + 777])
    exp_dec.end()
    assert exp_dec.finished

    dec, events = fresh()

    def source(ckpt, failures):
        remaining = len(_WIRE) - ckpt.wire_offset
        plan = FaultPlan.for_sweep(seed, remaining, attempt=failures)
        return FaultyReader(bytes_reader(_WIRE[ckpt.wire_offset:]), plan)

    stats = _with_watchdog(lambda: run_resumable(
        source, dec,
        BackoffPolicy(base=0.0005, cap=0.005, max_retries=8, seed=seed),
        chunk_size=1024, expected_total=len(_WIRE),
        stall_timeout=HARD_TIMEOUT / 2,
    ))
    assert stats is not None
    digests = [e for e in events if e[0] == "digest"]
    keys = [(k, s) for _, k, s, _ in digests]
    assert len(keys) == len(set(keys)), "duplicate digest delivery"
    assert events == expected  # values byte-identical, order preserved


# -- soak: 200 seeds (slow) -------------------------------------------------

@pytest.mark.slow
def test_sweep_soak_200_seeds():
    for seed in range(20, 220):
        stats, events = _run_seed(seed)
        assert stats is not None, f"seed {seed} errored on a resumable fault"
        assert events == _EXPECTED, f"seed {seed} diverged"


# -- corruption class: must ERROR with context, never resume ----------------

def test_flipped_header_type_id_errors_with_context():
    # frame 0's header is [varint len][type id]; the type id of the first
    # frame sits at byte 1 for single-byte-varint frames
    def source(ckpt, failures):
        plan = FaultPlan(seed=1, flip_at=1 - ckpt.wire_offset
                         if ckpt.wire_offset <= 1 else None, flip_mask=0x44)
        return FaultyReader(bytes_reader(_WIRE[ckpt.wire_offset:]), plan)

    dec, _events = _fresh_decoder()
    with pytest.raises(ProtocolError) as ei:
        _with_watchdog(lambda: run_resumable(
            source, dec, BackoffPolicy(base=0, max_retries=2, seed=0),
            expected_total=len(_WIRE), stall_timeout=5))
    err = ei.value
    assert "unknown type" in str(err)
    assert err.frame == 0 and err.offset is not None


def test_retries_exhausted_is_one_structured_error():
    def source(ckpt, failures):
        plan = FaultPlan(seed=2, drop_at=50)  # every attempt dies at 50
        return FaultyReader(bytes_reader(_WIRE[ckpt.wire_offset:]), plan)

    dec, _events = _fresh_decoder()
    policy = BackoffPolicy(base=0.0001, max_retries=3, seed=0)
    with pytest.raises(ProtocolError) as ei:
        _with_watchdog(lambda: run_resumable(
            source, dec, policy, expected_total=len(_WIRE), stall_timeout=5))
    err = ei.value
    assert "after 4 transport fault(s)" in str(err)
    assert isinstance(err.cause, TransportFault)
    assert err.offset is not None and err.frame is not None


def test_truncation_is_detected_not_silent():
    """A clean-looking EOF short of the sender's declared length must
    reconnect (detected truncation), finishing byte-identical."""
    calls = {"n": 0}

    def source(ckpt, failures):
        calls["n"] += 1
        plan = FaultPlan(seed=3,
                         truncate_at=len(_WIRE) // 3 if failures == 0 else None)
        return FaultyReader(bytes_reader(_WIRE[ckpt.wire_offset:]), plan)

    dec, events = _fresh_decoder()
    stats = _with_watchdog(lambda: run_resumable(
        source, dec, BackoffPolicy(base=0.0001, max_retries=2, seed=0),
        expected_total=len(_WIRE), stall_timeout=5))
    assert calls["n"] == 2 and stats["reconnects"] == 1
    assert "truncated" in stats["faults"][0]
    assert events == _EXPECTED


def test_mid_blob_disconnect_resumes_without_redelivery():
    """Drop inside the 3000-byte blob's payload: the checkpoint carries
    blob_offset > 0 and the resumed connection continues the SAME frame
    — delivered blob bytes must concatenate to exactly the payload."""
    # find a drop point inside the big blob: after ~70% of the wire
    drop_at = int(len(_WIRE) * 0.55)
    ckpts = []

    def source(ckpt, failures):
        ckpts.append(ckpt)
        plan = FaultPlan(seed=4, max_segment=256,
                         drop_at=(drop_at - ckpt.wire_offset)
                         if failures == 0 else None)
        return FaultyReader(bytes_reader(_WIRE[ckpt.wire_offset:]), plan)

    dec, events = _fresh_decoder()
    stats = _with_watchdog(lambda: run_resumable(
        source, dec, BackoffPolicy(base=0.0001, max_retries=2, seed=0),
        expected_total=len(_WIRE), stall_timeout=5))
    assert stats["reconnects"] == 1
    assert events == _EXPECTED
    # the second connection's checkpoint observed the fault point
    assert ckpts[1].wire_offset == drop_at


def _build_batch_wire() -> bytes:
    """The negotiated-session twin of ``_build_wire``: columnar
    ChangeBatch frames (several, so faults land INSIDE column blocks),
    interleaved blobs forcing flushes, and a per-record tail."""
    from dat_replication_protocol_tpu import BatchPolicy, CAP_CHANGE_BATCH

    e = protocol.encode(peer_caps=CAP_CHANGE_BATCH,
                        batch_policy=BatchPolicy(max_rows=40))
    j = WireJournal()
    e.attach_journal(j)
    for i in range(100):  # 2.5 batch frames' worth before the blob flush
        e.change({"key": f"bulk-{i % 16}", "change": i, "from": i,
                  "to": i + 1, "value": b"v%03d" % i,
                  "subset": "s" if i % 3 else None})
    big = e.blob(3000)
    big.write(b"x" * 1700)
    e.change({"key": "parked", "change": 99, "from": 0, "to": 1,
              "value": b"after-blob"})
    big.end(b"y" * 1300)
    for i in range(30):
        e.change({"key": f"tail-{i % 4}", "change": i, "from": i,
                  "to": i + 1})
    e.finalize()
    while e.read(4096) is not None:
        pass
    return j.read_from(0)


_BATCH_WIRE = _build_batch_wire()


def _expected_on(wire: bytes):
    dec, events = _fresh_decoder()
    for off in range(0, len(wire), 777):
        dec.write(wire[off:off + 777])
    dec.end()
    assert dec.finished
    return events


_BATCH_EXPECTED = _expected_on(_BATCH_WIRE)


def _run_seed_on(wire: bytes, seed: int):
    dec, events = _fresh_decoder()

    def source(ckpt, failures):
        remaining = len(wire) - ckpt.wire_offset
        plan = FaultPlan.for_sweep(seed, remaining, attempt=failures)
        return FaultyReader(bytes_reader(wire[ckpt.wire_offset:]), plan)

    def drive():
        return run_resumable(
            source, dec,
            BackoffPolicy(base=0.0005, cap=0.005, max_retries=8, seed=seed),
            chunk_size=256,  # small chunks: disconnects land mid-frame
            expected_total=len(wire),
            stall_timeout=HARD_TIMEOUT / 2,
        )

    try:
        stats = _with_watchdog(drive)
    except ProtocolError as e:
        assert e.offset is not None, f"unstructured ProtocolError: {e}"
        return None, None
    return stats, events


@pytest.mark.parametrize("seed", range(20))
def test_sweep_batch_frames_resume_exactly_once(seed):
    """Disconnect-class faults against a ChangeBatch-framed session:
    every seed converges and the decoded rows are exactly-once in order
    — resume across a batch boundary neither redelivers nor drops a
    row of the interrupted frame."""
    stats, events = _run_seed_on(_BATCH_WIRE, seed)
    assert stats is not None, "disconnect-class fault must resume, not error"
    assert events == _BATCH_EXPECTED


def _batch_frame_extent():
    """(payload_start, payload_len) of the first ChangeBatch frame."""
    import numpy as np

    from dat_replication_protocol_tpu.runtime import replay
    from dat_replication_protocol_tpu.wire.framing import TYPE_CHANGE_BATCH

    idx = replay.split_frames(np.frombuffer(_BATCH_WIRE, np.uint8))
    f = int(np.nonzero(idx.ids == TYPE_CHANGE_BATCH)[0][0])
    return int(idx.starts[f]), int(idx.lens[f])


def test_truncate_inside_batch_column_block_redelivers_exactly_once():
    start, flen = _batch_frame_extent()
    cut = start + flen // 2  # middle of the column block
    calls = {"n": 0}

    def source(ckpt, failures):
        calls["n"] += 1
        plan = FaultPlan(seed=7, truncate_at=(cut - ckpt.wire_offset)
                         if failures == 0 else None)
        return FaultyReader(
            bytes_reader(_BATCH_WIRE[ckpt.wire_offset:]), plan)

    dec, events = _fresh_decoder()
    stats = _with_watchdog(lambda: run_resumable(
        source, dec, BackoffPolicy(base=0.0001, max_retries=2, seed=0),
        expected_total=len(_BATCH_WIRE), stall_timeout=5))
    assert calls["n"] == 2 and stats["reconnects"] == 1
    assert events == _BATCH_EXPECTED  # every row exactly once


def test_flip_inside_batch_column_block_never_hangs():
    """A flipped byte inside the column block either trips the batch
    decoder's structural validation (ONE structured error with context)
    or lands in a value heap byte (delivered corrupt — the documented
    wire-layer limit, same as a blob payload flip).  Either way: never
    a hang, never a duplicate."""
    start, flen = _batch_frame_extent()
    for probe in (5, flen // 3, flen - 2):
        flip_at = start + probe

        def source(ckpt, failures, flip_at=flip_at):
            plan = FaultPlan(seed=9, flip_at=flip_at - ckpt.wire_offset,
                             flip_mask=0x40)
            return FaultyReader(
                bytes_reader(_BATCH_WIRE[ckpt.wire_offset:]), plan)

        dec, events = _fresh_decoder()
        try:
            stats = _with_watchdog(lambda: run_resumable(
                source, dec,
                BackoffPolicy(base=0, max_retries=0, seed=0),
                expected_total=len(_BATCH_WIRE), stall_timeout=5))
        except ProtocolError as e:
            assert e.offset is not None and e.frame is not None
            continue
        assert stats is not None
        # completed: rows delivered at most once (corrupt content is
        # possible; duplicates/hangs are not)
        keys = [ev for ev in events if ev[0] == "change"]
        assert len(keys) <= len(
            [ev for ev in _BATCH_EXPECTED if ev[0] == "change"])


# -- rateless reconciliation under chaos (ISSUE 10) --------------------------
#
# The anti-entropy contract: a faulted symbol stream either completes
# with the EXACT symmetric difference after resume, or raises ONE
# structured ProtocolError — never a wrong diff.  The initiator's wire
# (BEGIN + paced symbol batches + the requested records as ChangeBatch
# frames) is recorded once from a healthy run and replayed through the
# fault injector into a fresh responder per seed.


def _build_reconcile_wire():
    from dat_replication_protocol_tpu.runtime.reconcile_driver import (
        RatelessReplica,
        ResponderState,
    )
    from dat_replication_protocol_tpu.wire import reconcile_codec as rcc
    from dat_replication_protocol_tpu.wire.framing import CAP_CHANGE_BATCH, \
        CAP_RECONCILE

    keys = [f"rc-{i:04d}" for i in range(150)]
    a_recs = [{"key": k, "change": i, "from": i, "to": i + 1,
               "value": b"v:" + k.encode()}
              for i, k in enumerate(keys + ["a-only-1", "a-only-2"])]
    b_recs = [{"key": k, "change": i, "from": i, "to": i + 1,
               "value": b"v:" + k.encode()}
              for i, k in enumerate(keys + ["b-only-1"])]
    a = RatelessReplica(a_recs)
    state = ResponderState(RatelessReplica(b_recs))
    e = protocol.encode(peer_caps=CAP_RECONCILE | CAP_CHANGE_BATCH)
    j = WireJournal()
    e.attach_journal(j)
    payload = rcc.encode_begin(a.n)
    e.reconcile_frame(payload)
    state.handle(rcc.decode_reconcile(payload))
    syms = a.coded_symbols()
    sent, m = 0, 16
    while True:
        payload = rcc.encode_symbols(sent, syms.extend(m)[sent:])
        e.reconcile_frame(payload)
        sent = m
        replies = state.handle(rcc.decode_reconcile(payload))
        last = rcc.decode_reconcile(replies[-1])
        if last.kind == rcc.RC_DONE:
            rows = a.rows_for_digests(last.digests)
            e.change_many(a.records_for_rows(rows))
            break
        assert last.kind == rcc.RC_MORE
        m *= 2
    e.finalize()
    while e.read(4096) is not None:
        pass
    return j.read_from(0), b_recs


_RC_WIRE, _RC_B_RECS = _build_reconcile_wire()


def _fresh_reconcile_responder():
    from dat_replication_protocol_tpu.runtime.reconcile_driver import (
        RatelessReplica,
        ResponderState,
    )

    state = ResponderState(RatelessReplica(_RC_B_RECS))
    dec = protocol.decode()
    dec.reconcile(lambda msg, done: (state.handle(msg), done()))
    dec.change(lambda c, done: (state.note_remote_record(c), done()))
    return dec, state


def _rc_expected():
    dec, state = _fresh_reconcile_responder()
    for off in range(0, len(_RC_WIRE), 777):
        dec.write(_RC_WIRE[off:off + 777])
    dec.end()
    assert dec.finished
    digests, signs = state.result()
    diff = sorted((bytes(d), int(s)) for d, s in zip(digests, signs))
    recs = sorted(str(c) for c in state.remote_records)
    assert len(diff) == 3 and len(recs) == 2  # 2 a-only + 1 b-only
    return diff, recs


_RC_EXPECTED = _rc_expected()


def _run_reconcile_seed(seed: int):
    dec, state = _fresh_reconcile_responder()

    def source(ckpt, failures):
        remaining = len(_RC_WIRE) - ckpt.wire_offset
        plan = FaultPlan.for_sweep(seed, remaining, attempt=failures)
        return FaultyReader(bytes_reader(_RC_WIRE[ckpt.wire_offset:]), plan)

    def drive():
        return run_resumable(
            source, dec,
            BackoffPolicy(base=0.0005, cap=0.005, max_retries=8, seed=seed),
            chunk_size=256,  # small chunks: faults land mid-symbol-run
            expected_total=len(_RC_WIRE),
            stall_timeout=HARD_TIMEOUT / 2,
        )

    try:
        stats = _with_watchdog(drive)
    except ProtocolError as e:
        assert e.offset is not None, f"unstructured ProtocolError: {e}"
        return None, None
    try:
        digests, signs = state.result()
    except ProtocolError as e:
        assert e.offset is not None, f"unstructured ProtocolError: {e}"
        return None, None
    diff = sorted((bytes(d), int(s)) for d, s in zip(digests, signs))
    recs = sorted(str(c) for c in state.remote_records)
    return stats, (diff, recs)


@pytest.mark.parametrize("seed", range(20))
def test_sweep_reconcile_resumes_exact_diff(seed):
    """Disconnect-class faults inside the symbol stream: every seed
    must converge after resume with the EXACT symmetric difference and
    the exact record set — a resumed symbol stream continues (the
    decoder's accumulated symbols survive the transport), it never
    restarts or double-counts a run."""
    stats, out = _run_reconcile_seed(seed)
    assert stats is not None, "disconnect-class fault must resume, not error"
    assert out == _RC_EXPECTED


@pytest.mark.slow
def test_sweep_reconcile_soak_100_seeds():
    wrong = []
    for seed in range(20, 120):
        stats, out = _run_reconcile_seed(seed)
        if stats is not None and out != _RC_EXPECTED:
            wrong.append(seed)  # the one outcome the contract forbids
    assert not wrong, f"seeds {wrong} delivered a WRONG diff"


def _rc_symbol_frame_extent():
    """(payload_start, payload_len) of the first SYMBOLS frame."""
    import numpy as np

    from dat_replication_protocol_tpu.runtime import replay
    from dat_replication_protocol_tpu.wire.framing import TYPE_RECONCILE

    idx = replay.split_frames(np.frombuffer(_RC_WIRE, np.uint8))
    rc_frames = np.nonzero(idx.ids == TYPE_RECONCILE)[0]
    f = int(rc_frames[1])  # frame 0 is BEGIN; 1 is the first symbol run
    return int(idx.starts[f]), int(idx.lens[f])


def test_flip_inside_symbol_frame_never_delivers_wrong_diff():
    """A flipped byte inside a coded-symbol run must end in ONE
    structured ProtocolError (structural validation, a failed decode,
    or the end-of-stream incompleteness check) — recovering a wrong
    element needs a 64-bit checksum collision, so a completed decode is
    trusted and must equal the truth."""
    start, flen = _rc_symbol_frame_extent()
    for probe in (0, 3, flen // 2, flen - 1):
        flip_at = start + probe

        def source(ckpt, failures, flip_at=flip_at):
            plan = FaultPlan(seed=13, flip_at=flip_at - ckpt.wire_offset,
                             flip_mask=0x20)
            return FaultyReader(
                bytes_reader(_RC_WIRE[ckpt.wire_offset:]), plan)

        dec, state = _fresh_reconcile_responder()
        try:
            _with_watchdog(lambda: run_resumable(
                source, dec, BackoffPolicy(base=0, max_retries=0, seed=0),
                expected_total=len(_RC_WIRE), stall_timeout=5))
            digests, signs = state.result()
        except ProtocolError as e:
            assert e.offset is not None, f"unstructured: {e}"
            continue
        diff = sorted((bytes(d), int(s)) for d, s in zip(digests, signs))
        assert diff == _RC_EXPECTED[0], f"flip at +{probe} changed the diff"


def test_truncate_inside_symbol_frame_resumes_symbol_stream():
    """Truncation mid-symbol-run: the resumed connection continues the
    SAME symbol stream from the checkpoint byte — the peeler sees every
    cell exactly once and decodes the exact diff."""
    start, flen = _rc_symbol_frame_extent()
    cut = start + flen // 2
    calls = {"n": 0}

    def source(ckpt, failures):
        calls["n"] += 1
        plan = FaultPlan(seed=17, truncate_at=(cut - ckpt.wire_offset)
                         if failures == 0 else None)
        return FaultyReader(bytes_reader(_RC_WIRE[ckpt.wire_offset:]), plan)

    dec, state = _fresh_reconcile_responder()
    stats = _with_watchdog(lambda: run_resumable(
        source, dec, BackoffPolicy(base=0.0001, max_retries=2, seed=0),
        expected_total=len(_RC_WIRE), stall_timeout=5))
    assert calls["n"] == 2 and stats["reconnects"] == 1
    digests, signs = state.result()
    diff = sorted((bytes(d), int(s)) for d, s in zip(digests, signs))
    assert diff == _RC_EXPECTED[0]
    assert sorted(str(c) for c in state.remote_records) == _RC_EXPECTED[1]


def test_payload_flip_is_undetected_at_wire_layer():
    """Documented failure-model limit (ROBUSTNESS.md): a flipped byte
    inside a blob payload does not violate framing — the session
    completes with CORRUPT content.  The digest pipeline, not the wire
    layer, is the end-to-end integrity answer; this test pins the limit
    so a future in-band checksum shows up as a deliberate contract
    change."""
    # flip a byte deep inside the big blob's payload
    flip_at = int(len(_WIRE) * 0.55)

    def source(ckpt, failures):
        plan = FaultPlan(seed=5, flip_at=flip_at - ckpt.wire_offset)
        return FaultyReader(bytes_reader(_WIRE[ckpt.wire_offset:]), plan)

    dec, events = _fresh_decoder()
    stats = _with_watchdog(lambda: run_resumable(
        source, dec, BackoffPolicy(base=0, max_retries=0, seed=0),
        expected_total=len(_WIRE), stall_timeout=5))
    assert stats is not None and dec.finished
    assert events != _EXPECTED  # corrupt — and the wire layer cannot know
