"""Columnar ``ChangeBatch`` frames: codec, negotiation, mixed versions.

ISSUE 6 coverage:

* payload codec roundtrips (rows and columns tiers, C-vs-Python
  byte-exactness, absent-vs-present-empty, width-ladder edges) and
  structural-corruption rejection;
* **mixed-version sessions** — a capability-less encoder produces
  today's wire byte-exactly (new-encoder -> old-decoder golden), and the
  new decoder consumes per-record wire unchanged (old-encoder ->
  new-decoder);
* negotiated sessions end-to-end through every parse path (streaming
  scanner, chunked straddles, native bulk index), flush policy, blob
  ordering, backpressure, raise-then-resume;
* digest parity: a TPU-backend decoder emits identical digests for
  batch-framed and per-record-framed rows;
* bulk replay: ``replay_log`` over batch and mixed logs, the columnar
  batch encoder, canonical re-encode extents.
"""

from __future__ import annotations

import numpy as np
import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu import BatchPolicy, CAP_CHANGE_BATCH
from dat_replication_protocol_tpu.runtime import native, replay
from dat_replication_protocol_tpu.wire import batch_codec
from dat_replication_protocol_tpu.wire.change_codec import Change, \
    encode_change
from dat_replication_protocol_tpu.wire.framing import LOCAL_CAPS, \
    TYPE_CHANGE, TYPE_CHANGE_BATCH, frame


def drain(e) -> bytes:
    out = bytearray()
    while (c := e.read()) not in (None, b""):
        out += c
    return bytes(out)


def _records(n: int, keyspace: int = 16):
    return [
        Change(
            key=f"key-{i % keyspace:05d}",
            change=i,
            from_=i,
            to=i + 1,
            value=b"v" * (i % 13) if i % 5 else None,
            subset="s" if i % 3 else None,
        )
        for i in range(n)
    ]


def _rows(recs):
    return [
        (r.key.encode(), r.change, r.from_, r.to,
         None if r.value is None else bytes(r.value),
         None if r.subset is None else r.subset.encode())
        for r in recs
    ]


def _expected_dicts(recs):
    out = []
    for r in recs:
        d = r.to_dict()
        d["value"] = d["value"] if d["value"] is not None else b""
        d["subset"] = d["subset"] if d["subset"] is not None else ""
        out.append(d)
    return out


# -- payload codec -----------------------------------------------------------


def test_codec_roundtrip_rows_tier():
    recs = _records(500)
    payload = batch_codec.encode_rows(_rows(recs))
    cols = batch_codec.decode_change_batch(payload)
    assert len(cols.change) == 500
    got = [cols.row(i).to_dict() for i in range(500)]
    assert got == _expected_dicts(recs)


def test_codec_preserves_absent_vs_present_empty():
    recs = [
        Change(key="a", change=1, from_=0, to=1, value=None, subset=None),
        Change(key="a", change=2, from_=1, to=2, value=b"", subset=""),
    ]
    cols = batch_codec.decode_change_batch(
        batch_codec.encode_rows(_rows(recs)))
    assert int(cols.val_len[0]) == -1 and int(cols.sub_len[0]) == -1
    assert int(cols.val_len[1]) == 0 and int(cols.sub_len[1]) == 0


def test_codec_width_ladder_edges():
    # >255 distinct keys forces a 2-byte key index; a >255-byte value
    # forces a 2-byte value length; both survive the roundtrip
    recs = [Change(key=f"k{i:04d}", change=i, from_=0, to=1,
                   value=b"x" * (300 if i == 0 else i % 3))
            for i in range(300)]
    payload = batch_codec.encode_rows(_rows(recs))
    assert payload[1] == 2  # kw
    assert payload[3] == 2  # vw
    cols = batch_codec.decode_change_batch(payload)
    assert [cols.row(i).to_dict() for i in range(300)] \
        == _expected_dicts(recs)


def test_codec_c_and_python_paths_byte_identical(monkeypatch):
    if not native.available():
        pytest.skip("native library unavailable")
    recs = _records(700, keyspace=40)
    wire = b"".join(frame(TYPE_CHANGE, encode_change(r)) for r in recs)
    cols, _ = replay.replay_log(np.frombuffer(wire, np.uint8))
    c_payload = batch_codec.encode_columns(cols)
    monkeypatch.setenv("DAT_NATIVE_DISABLE", "1")
    py_payload = batch_codec.encode_columns(cols)
    assert c_payload == py_payload
    # and the rows tier (the session encoder's path) agrees too
    assert batch_codec.encode_rows(_rows(recs)) == c_payload


def test_codec_empty_batch_roundtrips():
    cols = batch_codec.decode_change_batch(batch_codec.encode_rows([]))
    assert len(cols.change) == 0


@pytest.mark.parametrize("mangle, what", [
    (lambda p: bytes([99]) + p[1:], "version"),
    (lambda p: p[:1] + bytes([3]) + p[2:], "widths"),
    (lambda p: p[:-3], "truncated"),
    (lambda p: p + b"xx", "trailing"),
])
def test_codec_rejects_structural_corruption(mangle, what):
    payload = batch_codec.encode_rows(_rows(_records(40)))
    with pytest.raises(ValueError):
        batch_codec.decode_change_batch(mangle(payload))


def test_codec_rejects_out_of_range_key_index():
    recs = [Change(key="only", change=1, from_=0, to=1)]
    payload = bytearray(batch_codec.encode_rows(_rows(recs)))
    payload[-1] = 7  # the single row's key index (1 key -> must be 0)
    with pytest.raises(ValueError):
        batch_codec.decode_change_batch(bytes(payload))


def test_codec_rejects_non_utf8_dictionary():
    recs = [Change(key="ab", change=1, from_=0, to=1)]
    payload = bytearray(batch_codec.encode_rows(_rows(recs)))
    at = bytes(payload).index(b"ab")
    payload[at] = 0xFF
    with pytest.raises(ValueError):
        batch_codec.decode_change_batch(bytes(payload))


def test_codec_rejects_entry_splitting_multibyte_char():
    # two keys whose heaps concatenate to VALID utf-8 ("é" split as
    # continuation start of key 2) must still be rejected per entry
    rows = [(b"a\xc3", 1, 0, 1, None, None),
            (b"\xa9b", 2, 1, 2, None, None)]
    payload = batch_codec.encode_rows(rows)
    with pytest.raises(ValueError):
        batch_codec.decode_change_batch(payload)


# -- mixed versions: the golden old-peer contract ---------------------------


def test_capability_less_encoder_is_byte_identical_to_reference_wire():
    """New-encoder -> old-decoder: a session that never negotiated emits
    today's exact bytes (the test_wire_fixtures transcripts re-derived
    here against a default-constructed encoder)."""
    e = protocol.encode()  # no peer_caps: the old wire, byte-exact
    e.change({"key": "key", "from": 0, "to": 1, "change": 1,
              "value": b"hello"})
    b = e.blob(11)
    b.write(b"hello ")
    b.write(b"world")
    b.end()
    payload = bytes.fromhex("12036b657918012000280132 0568656c6c6f"
                            .replace(" ", ""))
    assert drain(e) == (bytes([0x13, 0x01]) + payload
                       + bytes([0x0C, 0x02]) + b"hello world")


def test_old_encoder_wire_through_new_decoder_unchanged():
    """Old-encoder -> new-decoder: per-record frames decode exactly as
    before the batch extension existed (every chunking)."""
    recs = _records(60)
    wire = b"".join(frame(TYPE_CHANGE, encode_change(r)) for r in recs)
    for size in (1, 7, len(wire)):
        d = protocol.decode()
        got = []
        d.change(lambda c, done: (got.append(c.to_dict()), done()))
        for off in range(0, len(wire), size):
            d.write(wire[off:off + size])
        d.end()
        assert d.finished and got == _expected_dicts(recs), size


def test_batch_frame_to_capability_less_peer_is_the_unknown_type_error():
    """The other direction of negotiation: a peer that did NOT advertise
    the capability rejects the frame id — which is exactly why an
    encoder must never emit it unnegotiated.  (The reference decoder
    fails the same way on any unknown id.)"""
    payload = batch_codec.encode_rows(_rows(_records(3)))
    wire = frame(TYPE_CHANGE_BATCH, payload)

    class OldDecoder(protocol.Decoder):
        # yesterday's parser: no batch dispatch
        def _finish_change_batch(self, payload):
            raise AssertionError("unreachable in this simulation")

        def _scan_header(self, chunk):
            return protocol.Decoder._scan_header(self, chunk)

    d = protocol.decode()
    errs = []
    d.on_error(lambda e: errs.append(e))
    d.write(wire)  # the NEW decoder accepts it...
    assert not errs and d.changes == 3

    # ...and the negotiation constants say when it may be sent
    assert protocol.Decoder.capabilities() == LOCAL_CAPS
    assert LOCAL_CAPS & CAP_CHANGE_BATCH


# -- negotiated sessions end-to-end -----------------------------------------


def _negotiated_session(n=250, policy=None, **enc_kw):
    e = protocol.encode(peer_caps=CAP_CHANGE_BATCH,
                        batch_policy=policy, **enc_kw)
    recs = _records(n)
    for r in recs:
        e.change(r)
    e.finalize()
    return drain(e), recs


@pytest.mark.parametrize("size", [1, 9, 4096, 1 << 20])
def test_negotiated_wire_delivers_per_row_on_every_parse_path(size):
    wire, recs = _negotiated_session(300, BatchPolicy(max_rows=64))
    d = protocol.decode()
    got = []
    d.change(lambda c, done: (got.append(c.to_dict()), done()))
    for off in range(0, len(wire), size):
        d.write(wire[off:off + size])
    d.end()
    assert d.finished
    assert got == _expected_dicts(recs)
    assert d.changes == 300


def test_change_batch_handler_gets_whole_columns():
    wire, recs = _negotiated_session(200)
    d = protocol.decode()
    batches = []
    d.change_batch(lambda cols, done: (batches.append(cols), done()))
    d.write(wire)
    d.end()
    assert d.finished and d.changes == 200
    assert sum(len(b.change) for b in batches) == 200
    assert batches[0].row(0).to_dict() == _expected_dicts(recs)[0]


def test_flush_policy_max_rows_sizes_frames():
    wire, _ = _negotiated_session(250, BatchPolicy(max_rows=100))
    frames_idx = replay.split_frames(np.frombuffer(wire, np.uint8))
    batch = frames_idx.ids == TYPE_CHANGE_BATCH
    assert int(batch.sum()) == 3  # 100 + 100 + 50 (finalize flush)


def test_blob_flushes_pending_rows_first():
    e = protocol.encode(peer_caps=CAP_CHANGE_BATCH)
    e.change({"key": "before", "change": 1, "from": 0, "to": 1})
    b = e.blob(3)
    b.end(b"xyz")
    e.change({"key": "after", "change": 2, "from": 1, "to": 2})
    e.finalize()
    wire = drain(e)
    d = protocol.decode()
    events = []
    d.change(lambda c, done: (events.append(("change", c.key)), done()))
    d.blob(lambda bl, done: bl.collect(
        lambda data: (events.append(("blob", data)), done())))
    d.write(wire)
    d.end()
    assert events == [("change", "before"), ("blob", b"xyz"),
                      ("change", "after")]


def test_read_uncorks_pending_rows():
    e = protocol.encode(peer_caps=CAP_CHANGE_BATCH)
    e.change({"key": "k", "change": 1, "from": 0, "to": 1})
    # no flush trigger fired yet — but a hungry consumer must not wait
    data = e.read()
    assert data and data[1] == TYPE_CHANGE_BATCH


def test_max_delay_flushes_on_next_submit():
    e = protocol.encode(peer_caps=CAP_CHANGE_BATCH,
                        batch_policy=BatchPolicy(max_delay=0.0))
    e.change({"key": "a", "change": 1, "from": 0, "to": 1})
    # delay 0: the NEXT submit sees the deadline expired and flushes
    e.change({"key": "b", "change": 2, "from": 1, "to": 2})
    assert e.bytes > 0  # first flush happened without finalize


def test_negotiate_revocation_reframes_pending_rows_per_record():
    """Revoking the capability means the peer CANNOT parse a batch
    frame — rows pending at revocation must re-frame per-record, so a
    reference peer sees only frame ids it understands."""
    e = protocol.encode()
    e.negotiate(CAP_CHANGE_BATCH)
    fired = []
    e.change({"key": "a", "change": 1, "from": 0, "to": 1,
              "value": b"x", "subset": "s"},
             on_flush=lambda: fired.append(1))
    e.negotiate(0)
    e.change({"key": "b", "change": 2, "from": 1, "to": 2})
    e.finalize()
    wire = drain(e)
    idx = replay.split_frames(np.frombuffer(wire, np.uint8))
    assert idx.ids.tolist() == [TYPE_CHANGE, TYPE_CHANGE]
    assert fired == [1]  # the pending row's flush callback still fires
    # and the re-framed bytes are the canonical per-record encoding
    assert wire == frame(TYPE_CHANGE, encode_change(
        Change(key="a", change=1, from_=0, to=1, value=b"x", subset="s"))
    ) + frame(TYPE_CHANGE, encode_change(
        Change(key="b", change=2, from_=1, to=2)))


def test_on_flush_callbacks_fire_when_batch_drains():
    e = protocol.encode(peer_caps=CAP_CHANGE_BATCH)
    fired = []
    e.change({"key": "a", "change": 1, "from": 0, "to": 1},
             on_flush=lambda: fired.append("a"))
    e.change({"key": "b", "change": 2, "from": 1, "to": 2},
             on_flush=lambda: fired.append("b"))
    assert fired == []
    e.finalize()
    drain(e)
    assert fired == ["a", "b"]


def test_batch_pending_rows_count_toward_high_water():
    e = protocol.encode(high_water=256, peer_caps=CAP_CHANGE_BATCH,
                        batch_policy=BatchPolicy(max_rows=1 << 30,
                                                 max_bytes=1 << 30))
    ok = True
    for i in range(40):
        ok = e.change({"key": f"k-{i}", "change": i, "from": i, "to": i + 1})
    assert not ok and not e.writable()


def test_bad_row_raises_at_submit_not_flush():
    e = protocol.encode(peer_caps=CAP_CHANGE_BATCH)
    with pytest.raises(ValueError):
        e.change({"key": "k", "change": -1, "from": 0, "to": 1})
    with pytest.raises(KeyError):
        e.change({"key": "k", "change": 1, "to": 1})
    # the session is still healthy; pending state unpolluted
    e.change({"key": "k", "change": 1, "from": 0, "to": 1})
    e.finalize()
    assert drain(e)


def test_mid_batch_async_ack_stalls_and_resumes_in_order():
    wire, recs = _negotiated_session(30)
    d = protocol.decode()
    rows, pend = [], []

    def handler(c, done):
        rows.append(c.change)
        if c.change == 10:
            pend.append(done)
        else:
            done()

    d.change(handler)
    assert not d.write(wire)
    assert rows == list(range(11)) and not d.writable()
    d.end()
    assert not d.finished
    pend.pop()()
    assert d.finished and rows == list(range(30))


def test_mid_batch_handler_raise_resumes_at_next_row():
    wire, _ = _negotiated_session(20)
    d = protocol.decode()
    rows = []

    def handler(c, done):
        rows.append(c.change)
        if c.change == 5 and rows.count(5) == 1:
            raise RuntimeError("app hiccup")
        done()

    d.change(handler)
    with pytest.raises(RuntimeError):
        d.write(wire)
    assert rows == list(range(6))
    d.write(b"")  # caught-and-continue: next write resumes the cursor
    d.end()
    assert d.finished and rows == list(range(20))  # no redelivery


def test_corrupt_batch_payload_is_structured_protocol_error():
    payload = batch_codec.encode_rows(_rows(_records(10)))
    bad = bytearray(frame(TYPE_CHANGE_BATCH, payload))
    bad[3] = 0xEE  # inside the width header: structurally corrupt
    d = protocol.decode()
    errs = []
    d.on_error(lambda e: errs.append(e))
    d.write(bytes(bad))
    assert d.destroyed and len(errs) == 1
    assert errs[0].frame == 0 and errs[0].offset is not None


def test_frames_delivered_counts_batches_as_single_frames():
    wire, _ = _negotiated_session(100, BatchPolicy(max_rows=50))
    d = protocol.decode()
    d.change(lambda c, done: done())
    d.write(wire)
    d.end()
    assert d.changes == 100
    assert d._frames_delivered() == 2  # two 50-row frames
    ckpt = d.checkpoint()
    assert ckpt.frame == 2 and ckpt.row == 100
    assert ckpt.wire_offset == len(wire)


def test_change_many_per_record_mode_matches_per_call_bytes():
    recs = _records(50)
    e1 = protocol.encode()
    for r in recs:
        e1.change(r)
    e1.finalize()
    e2 = protocol.encode()
    fired = []
    e2.change_many(recs, on_flush=lambda: fired.append(1))
    e2.finalize()
    assert drain(e1) == drain(e2)
    assert fired == [1] and e2.changes == 50


def test_change_many_batching_mode_delivers_all_rows():
    recs = _records(50)
    e = protocol.encode(peer_caps=CAP_CHANGE_BATCH)
    e.change_many(recs)
    e.finalize()
    d = protocol.decode()
    got = []
    d.change(lambda c, done: (got.append(c.to_dict()), done()))
    d.write(drain(e))
    d.end()
    assert got == _expected_dicts(recs)


# -- digest parity (TPU backend) --------------------------------------------


def _digests(wire: bytes):
    d = protocol.decode(backend="tpu")
    out = []
    d.on_digest(lambda kind, seq, dg: out.append((kind, seq, dg)))
    d.change(lambda c, done: done())
    d.blob(lambda b, done: b.collect(lambda _x: done()))
    d.write(wire)
    d.end()
    assert d.finished
    return out


def test_tpu_encoder_digest_stream_survives_batch_negotiation():
    """Send-side digest parity: a negotiated TpuEncoder delivers the
    SAME (kind, seq, digest) stream per-record framing would have —
    batch flushes submit each row's canonical encoding."""
    recs = _records(40)

    def encoder_digests(**kw):
        e = protocol.encode(backend="tpu", **kw)
        out = []
        e.on_digest(lambda kind, seq, dg: out.append((kind, seq, dg)))
        for r in recs:
            e.change(r)
        w = e.blob(4)
        w.end(b"data")
        e.finalize()
        drain(e)
        e.digest_pipeline.flush()
        return out

    assert encoder_digests(peer_caps=CAP_CHANGE_BATCH) == encoder_digests()
    assert len(encoder_digests()) == 41  # 40 changes + 1 blob


def test_digest_stream_identical_for_batch_and_per_record_wire():
    recs = _records(64)
    per_record = b"".join(frame(TYPE_CHANGE, encode_change(r))
                          for r in recs)
    e = protocol.encode(peer_caps=CAP_CHANGE_BATCH)
    for r in recs:
        e.change(r)
    w = e.blob(4)
    w.end(b"data")
    e.finalize()
    batched = drain(e)
    assert _digests(per_record + frame(2, b"data")) == _digests(batched)


# -- bulk replay -------------------------------------------------------------


def _cols_equal(a, b) -> bool:
    n = len(a.change)
    if n != len(b.change):
        return False
    return all(a.row(i).to_dict() == b.row(i).to_dict()
               for i in range(0, n, max(1, n // 64)))


def test_replay_log_over_batch_wire_matches_per_record_wire():
    recs = _records(5000, keyspace=128)
    pr_wire = b"".join(frame(TYPE_CHANGE, encode_change(r)) for r in recs)
    cols_pr, _ = replay.replay_log(np.frombuffer(pr_wire, np.uint8))
    b_wire = replay.encode_batch_frames(cols_pr, rows_per_batch=1024)
    assert len(b_wire) < len(pr_wire)  # the dictionary earns its bytes
    cols_b, frames_b = replay.replay_log(np.frombuffer(b_wire, np.uint8))
    assert _cols_equal(cols_pr, cols_b)
    assert int((frames_b.ids == TYPE_CHANGE_BATCH).sum()) == 5


def test_replay_log_mixed_frames_keeps_wire_order():
    recs = _records(30)
    pr = b"".join(frame(TYPE_CHANGE, encode_change(r)) for r in recs[:10])
    cols_mid, _ = replay.replay_log(np.frombuffer(
        b"".join(frame(TYPE_CHANGE, encode_change(r))
                 for r in recs[10:20]), np.uint8))
    mid = replay.encode_batch_frames(cols_mid)
    tail = b"".join(frame(TYPE_CHANGE, encode_change(r))
                    for r in recs[20:])
    blob = frame(2, b"BLOB")
    mixed = pr + blob + mid + tail
    cols, frames = replay.replay_log(np.frombuffer(mixed, np.uint8))
    assert [cols.row(i).to_dict() for i in range(30)] \
        == _expected_dicts(recs)


def test_canonical_payloads_match_per_record_encodings():
    recs = _records(40)
    e = protocol.encode(peer_caps=CAP_CHANGE_BATCH)
    for r in recs:
        e.change(r)
    e.finalize()
    cols, _ = replay.replay_log(np.frombuffer(drain(e), np.uint8))
    assert replay.canonical_change_payloads(cols) \
        == [encode_change(r) for r in recs]


def test_leaves_from_columns_falls_back_for_batch_logs():
    from dat_replication_protocol_tpu.batch import feed

    recs = _records(32)
    pr_wire = b"".join(frame(TYPE_CHANGE, encode_change(r)) for r in recs)
    cols_pr, frames_pr = replay.replay_log(np.frombuffer(pr_wire, np.uint8))
    b_wire = replay.encode_batch_frames(cols_pr)
    cols_b, frames_b = replay.replay_log(np.frombuffer(b_wire, np.uint8))
    leaves_pr = feed.leaves_from_columns(cols_pr, frames_pr)
    leaves_b = feed.leaves_from_columns(cols_b, frames_b)
    assert np.array_equal(leaves_pr, leaves_b)


def test_decode_batch_device_matches_host_columns():
    from dat_replication_protocol_tpu.batch import feed

    recs = _records(100)
    payload = batch_codec.encode_rows(_rows(recs))
    dev = feed.decode_batch_device(payload)
    assert len(dev) == 100
    cols = batch_codec.decode_change_batch(payload)
    assert np.array_equal(np.asarray(dev.change), cols.change)
    assert np.array_equal(np.asarray(dev.from_), cols.from_)
    assert np.array_equal(np.asarray(dev.to), cols.to)
    assert np.array_equal(np.asarray(dev.val_off), cols.val_off)
    # the device-resident buffer serves value gathers directly
    vo, vl = int(cols.val_off[1]), int(cols.val_len[1])
    assert bytes(np.asarray(dev.buf[vo:vo + vl]).tobytes()) \
        == bytes(recs[1].value)


def test_wire_batch_counters_account_rows_and_savings(obs_enabled):
    from dat_replication_protocol_tpu.obs.metrics import REGISTRY

    e = protocol.encode(peer_caps=CAP_CHANGE_BATCH)
    recs = _records(200, keyspace=8)  # hot keys: the dictionary saves
    for r in recs:
        e.change(r)
    e.finalize()
    wire = drain(e)
    d = protocol.decode()
    d.change(lambda c, done: done())
    d.write(wire)
    d.end()
    counters = REGISTRY.snapshot()["counters"]
    assert counters["wire.batch.frames"] == 1
    assert counters["wire.batch.rows"] == 200
    per_record = sum(
        len(frame(TYPE_CHANGE, encode_change(r))) for r in recs)
    assert counters["wire.batch.bytes_saved"] == per_record - len(wire)
    assert counters["decoder.batch.frames"] == 1
    assert counters["decoder.changes"] == 200


def test_python_fallback_decoder_paths(monkeypatch):
    """The whole negotiated path with every native tier disabled: same
    rows, same order (the vectorized-Python tier contract)."""
    monkeypatch.setenv("DAT_NATIVE_DISABLE", "1")
    monkeypatch.setenv("DAT_FASTPATH_DISABLE", "1")
    wire, recs = _negotiated_session(120, BatchPolicy(max_rows=48))
    d = protocol.decode()
    got = []
    d.change(lambda c, done: (got.append(c.to_dict()), done()))
    for off in range(0, len(wire), 31):
        d.write(wire[off:off + 31])
    d.end()
    assert d.finished and got == _expected_dicts(recs)
