"""Unit coverage of the fault-and-recovery layer's parts.

The sweep (test_session_faults.py) proves the whole; these tests pin
each part's contract: checkpoint contents, journal window semantics,
backoff policy math, the transport pump's immediate drain wakeup (the
lost-wakeup fix), the fd close-once guard, the sidecar's retry flags,
and the asyncio reconnect face.
"""

from __future__ import annotations

import asyncio
import os
import threading
import time

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.session import transport
from dat_replication_protocol_tpu.session.aio import (
    open_connection_with_retry,
    send_over_async,
)
from dat_replication_protocol_tpu.session.reconnect import (
    BackoffPolicy,
    retrying,
)
from dat_replication_protocol_tpu.session.resume import (
    ResumeError,
    SessionCheckpoint,
    WireJournal,
)
from dat_replication_protocol_tpu.wire.framing import ProtocolError


# -- SessionCheckpoint ------------------------------------------------------

def test_checkpoint_tracks_the_coupled_cursor_tuple():
    e, d = protocol.encode(), protocol.decode()
    d.change(lambda c, done: done())
    d.blob(lambda b, done: (b.on_data(lambda _c: None), b.on_end(done)))
    e.change({"key": "a", "change": 1, "from": 0, "to": 1})
    ws = e.blob(100)
    ws.write(b"x" * 100)
    ws.end()
    e.finalize()
    wire = e.read()

    # feed everything but the blob's last 30 payload bytes
    d.write(wire[:-30])
    ck = d.checkpoint()
    assert ck.wire_offset == len(wire) - 30
    assert ck.frame == 1          # the change delivered; blob still open
    assert ck.row == 1
    assert ck.blob_offset == 70   # mid-blob cursor
    d.write(wire[-30:])
    d.end()
    assert d.finished
    ck2 = d.checkpoint()
    assert ck2.wire_offset == len(wire) and ck2.frame == 2
    assert ck2.blob_offset == 0


def test_checkpoint_roundtrips_through_dict():
    ck = SessionCheckpoint(wire_offset=7, frame=2, row=1, blob_offset=3,
                           digest={"change_seq": 1, "blob_seq": 0})
    assert SessionCheckpoint.from_dict(ck.as_dict()) == ck


def test_tpu_checkpoint_carries_digest_seq_state():
    d = protocol.decode(backend="tpu")
    d.on_digest(lambda *a: None)
    from dat_replication_protocol_tpu.wire.change_codec import encode_change
    from dat_replication_protocol_tpu.wire.framing import TYPE_CHANGE, frame

    d.write(frame(TYPE_CHANGE, encode_change(
        {"key": "k", "change": 1, "from": 0, "to": 1})))
    assert d.checkpoint().digest == {"change_seq": 1, "blob_seq": 0}


# -- WireJournal ------------------------------------------------------------

def test_journal_window_ack_and_read_from():
    j = WireJournal()
    j.append(b"abcdef")
    j.append(b"ghij")
    assert (j.start, j.end) == (0, 10)
    assert j.read_from(4) == b"efghij"
    assert j.read_from(10) == b""
    j.ack(6)
    assert (j.start, j.end) == (6, 10)
    assert j.read_from(6) == b"ghij"
    with pytest.raises(ResumeError) as ei:
        j.read_from(3)  # acked past: the window is gone
    assert ei.value.offset == 3
    with pytest.raises(ResumeError):
        j.read_from(11)  # ahead of production
    with pytest.raises(ValueError):
        j.ack(99)


def test_journal_trim_is_min_offset_aware_across_readers():
    """Regression (ISSUE 9 satellite): the original ack-trim assumed a
    single reader — with two attached cursors, one reader's ack must
    not trim the other reader's unread window."""
    j = WireJournal()
    j.append(b"0123456789")
    j.attach_reader("fast", 0)
    j.attach_reader("slow", 0)
    j.ack(8, reader="fast")
    # the slow reader still pins the window: nothing trimmed
    assert (j.start, j.end) == (0, 10)
    assert j.read_from(0) == b"0123456789"
    j.ack(5, reader="slow")
    assert (j.start, j.end) == (5, 10)  # trimmed to the MINIMUM ack
    # a bare (reader-less) ack is floored by the slowest reader too
    j.ack(9)
    assert j.start == 5
    # a departed laggard releases its pin on the next ack
    j.detach_reader("slow")
    j.ack(8, reader="fast")
    assert j.start == 8
    with pytest.raises(ValueError):
        j.ack(99, reader="fast")  # beyond production
    with pytest.raises(ValueError):
        j.ack(99)  # a bare over-end ack is a caller bug on EVERY
        # path — the reader floor must not silently mask it
    with pytest.raises(ValueError):
        j.ack(9, reader="ghost")  # unknown cursor


def test_journal_second_cursor_past_trim_point_is_structured():
    """Regression (ISSUE 9 satellite): attaching a cursor below the
    trimmed window must raise ResumeError carrying the retained range
    in the message — not silently short-read from the wrong place."""
    j = WireJournal()
    j.append(b"x" * 100)
    j.attach_reader("r1", 0)
    j.ack(60, reader="r1")  # sole reader: trims to 60
    assert j.start == 60
    with pytest.raises(ResumeError) as ei:
        j.attach_reader("r2", 40)  # past the trim point
    assert ei.value.offset == 40
    assert "[60, 100)" in str(ei.value)  # the retained range, in-message
    with pytest.raises(ResumeError) as ei:
        j.read_from(40)
    assert "[60, 100)" in str(ei.value)
    with pytest.raises(ResumeError):
        j.attach_reader("r3", 101)  # ahead of production
    # attaching INSIDE the retained range still works
    j.attach_reader("ok", 70)
    assert j.read_from(70) == b"x" * 30


def test_encoder_journal_tee_is_byte_exact_and_order_preserving():
    e = protocol.encode()
    j = WireJournal()
    e.attach_journal(j)
    e.change({"key": "a", "change": 1, "from": 0, "to": 1, "value": b"v"})
    ws = e.blob(5)
    ws.write(b"12")
    ws.end(b"345")
    e.finalize()
    parts = []
    while True:
        d = e.read(7)  # odd chunk size: bytes cross read boundaries
        if d is None:
            break
        parts.append(d)
    assert j.read_from(0) == b"".join(parts)
    assert j.end == e.bytes


# -- BackoffPolicy ----------------------------------------------------------

def test_backoff_full_jitter_is_bounded_and_seeded():
    p1 = BackoffPolicy(base=0.1, cap=1.0, max_retries=9, seed=42)
    p2 = BackoffPolicy(base=0.1, cap=1.0, max_retries=9, seed=42)
    delays = [p1.delay(k) for k in range(1, 10)]
    assert delays == [p2.delay(k) for k in range(1, 10)]  # reproducible
    for k, d in enumerate(delays, start=1):
        assert 0.0 <= d <= min(1.0, 0.1 * 2 ** k)  # full-jitter envelope
    assert max(delays) <= 1.0  # cap honored at high attempt counts


def test_retrying_bounded_attempts_then_structured_error():
    calls = {"n": 0}
    slept = []

    def flaky():
        calls["n"] += 1
        raise OSError("nope")

    policy = BackoffPolicy(base=0.01, max_retries=3, seed=0,
                           sleep=slept.append)
    with pytest.raises(ProtocolError) as ei:
        retrying(flaky, policy, describe="dial")
    assert calls["n"] == 4  # initial + 3 retries
    assert len(slept) == 3
    assert "dial failed after 4 attempt(s)" in str(ei.value)
    assert isinstance(ei.value.cause, OSError)


def test_retrying_recovers_midway():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("warming up")
        return "ok"

    policy = BackoffPolicy(base=0, max_retries=5, seed=0)
    assert retrying(flaky, policy) == "ok"
    assert calls["n"] == 3


# -- structured ProtocolError ------------------------------------------------

def test_protocol_error_context_renders_and_is_introspectable():
    cause = OSError("link down")
    err = ProtocolError("session lost", frame=7, offset=4242, cause=cause)
    assert err.frame == 7 and err.offset == 4242 and err.cause is cause
    s = str(err)
    assert "frame=7" in s and "byte=4242" in s and "link down" in s
    # bare form unchanged
    assert str(ProtocolError("plain")) == "plain"


def test_decoder_errors_carry_frame_and_byte_context():
    from dat_replication_protocol_tpu.wire.change_codec import encode_change
    from dat_replication_protocol_tpu.wire.framing import TYPE_CHANGE, frame

    d = protocol.decode()
    errs = []
    d.on_error(errs.append)
    d.write(frame(TYPE_CHANGE, encode_change(
        {"key": "k", "change": 1, "from": 0, "to": 1})))  # one good change
    d.write(b"\x05\x07xxxx")  # unknown type id 7
    assert d.destroyed
    (err,) = errs
    assert isinstance(err, ProtocolError)
    assert err.frame == 1  # one frame delivered before the bad one
    assert err.offset is not None and err.offset > 0


# -- transport: drain watcher (the lost-wakeup fix) --------------------------

def test_recv_over_wakes_immediately_on_cross_thread_ack():
    """The old pump polled every 50ms; the drain watcher must wake it
    as soon as the ack lands.  We hold the decoder's first-change ack,
    release it from another thread, and require end-to-end completion
    far faster than one poll period would allow if wakeups were lost."""
    e, d = protocol.encode(), protocol.decode()
    acks = []
    got = []
    d.change(lambda c, done: (got.append(c.key), acks.append(done)))

    for i in range(3):
        e.change({"key": f"k{i}", "change": i, "from": i, "to": i + 1})
    e.finalize()
    wire = e.read()

    def release():
        # ack each change ~5ms after it arrives, from OUR thread — every
        # wakeup crosses threads
        deadline = time.monotonic() + 10
        while not d.finished and time.monotonic() < deadline:
            if acks:
                acks.pop(0)()
            time.sleep(0.005)

    t = threading.Thread(target=release, daemon=True)
    t.start()
    t0 = time.monotonic()
    transport.recv_over(d, _mk_reader(wire), chunk_size=4096)
    elapsed = time.monotonic() - t0
    t.join(5)
    assert d.finished and got == ["k0", "k1", "k2"]
    # 3 cross-thread acks at ~5ms spacing: event-driven completes in
    # tens of ms.  The bound sits BELOW one WAKE_FALLBACK period (0.5s)
    # on purpose — with the watcher disabled, every stall costs a full
    # fallback poll and this fails (verified), so a regression that
    # silently breaks the event-driven wakeup cannot ship green
    assert elapsed < 0.4


def _mk_reader(data: bytes):
    from dat_replication_protocol_tpu.session.faults import bytes_reader

    return bytes_reader(data)


def test_decoder_drain_watcher_add_remove():
    d = protocol.decode()
    hits = []
    d._add_drain_watcher(lambda: hits.append(1))
    d.destroy()
    assert hits  # destroy wakes watchers
    d2 = protocol.decode()
    cb = lambda: hits.append(2)  # noqa: E731
    d2._add_drain_watcher(cb)
    d2._remove_drain_watcher(cb)
    d2._remove_drain_watcher(cb)  # double-remove is a no-op
    d2.destroy()
    assert hits == [1]


# -- transport: fd close-once guard -----------------------------------------

def test_send_over_fd_closes_exactly_once_and_guard_is_shareable():
    e = protocol.encode()
    e.change({"key": "k", "change": 1, "from": 0, "to": 1})
    e.finalize()
    r, w = os.pipe()
    closed = []
    real_close = os.close

    guard = transport.once(lambda: (closed.append(w), real_close(w)))
    got = []
    reader = threading.Thread(
        target=lambda: got.append(_read_all(r)), daemon=True)
    reader.start()
    returned = transport.send_over_fd(e, w, close=guard)
    assert returned is guard
    # the caller's own error-path cleanup calls the guard again: no
    # EBADF, no double close of a possibly-reused fd number
    guard()
    guard()
    assert closed == [w]
    reader.join(5)
    assert not reader.is_alive()  # the close delivered EOF to the peer
    os.close(r)
    assert got and len(got[0]) == e.bytes


def _read_all(fd: int):
    chunks = []
    while True:
        b = os.read(fd, 4096)
        if not b:
            return b"".join(chunks)
        chunks.append(b)


def test_once_guard_is_thread_safe():
    ran = []
    guard = transport.once(lambda: ran.append(1))
    ts = [threading.Thread(target=guard) for _ in range(16)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(5)
    assert ran == [1]


# -- sidecar: retry flags ----------------------------------------------------

def test_sidecar_bind_retries_through_transient_eaddrinuse():
    import socket as socket_mod

    from dat_replication_protocol_tpu import sidecar

    blocker = socket_mod.socket()
    blocker.bind(("127.0.0.1", 0))
    port = blocker.getsockname()[1]
    # no SO_REUSEADDR on the blocker + no listen: bind on the same port
    # fails while it lives; release it from a timer mid-retry
    threading.Timer(0.15, blocker.close).start()
    ready = threading.Event()
    policy = BackoffPolicy(base=0.1, cap=0.2, max_retries=10, seed=1)

    def serve():
        sidecar.serve_tcp("127.0.0.1", port, max_sessions=0,
                          ready_cb=lambda p: ready.set(),
                          retry_policy=policy)

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    assert ready.wait(10), "bind never succeeded after the blocker left"
    t.join(10)
    assert not t.is_alive()


def test_sidecar_bind_gives_up_with_structured_error():
    import socket as socket_mod

    from dat_replication_protocol_tpu import sidecar

    blocker = socket_mod.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    try:
        policy = BackoffPolicy(base=0.001, cap=0.002, max_retries=2, seed=1)
        with pytest.raises(ProtocolError) as ei:
            sidecar.serve_tcp("127.0.0.1", port, max_sessions=0,
                              retry_policy=policy)
        assert "bind" in str(ei.value) and isinstance(ei.value.cause, OSError)
    finally:
        blocker.close()


def test_sidecar_cli_accepts_retry_flags(capsys):
    from dat_replication_protocol_tpu import sidecar

    with pytest.raises(SystemExit):
        sidecar.main(["--stdio", "--max-retries", "bad"])
    # flags parse and reach the policy: exercised via --help text
    with pytest.raises(SystemExit):
        sidecar.main(["--help"])
    out = capsys.readouterr().out
    assert "--max-retries" in out and "--backoff-base" in out


# -- asyncio face ------------------------------------------------------------

def test_open_connection_with_retry_dials_until_server_appears():
    async def main():
        import socket as socket_mod

        probe = socket_mod.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()  # port now free — and nothing listens yet

        server_box = {}

        async def start_server_later():
            await asyncio.sleep(0.1)
            server_box["srv"] = await asyncio.start_server(
                lambda r, w: None, "127.0.0.1", port)

        starter = asyncio.ensure_future(start_server_later())
        policy = BackoffPolicy(base=0.05, cap=0.1, max_retries=20, seed=3)
        reader, writer = await open_connection_with_retry(
            "127.0.0.1", port, policy)
        writer.close()
        await starter
        server_box["srv"].close()
        await server_box["srv"].wait_closed()

    asyncio.run(asyncio.wait_for(main(), 30))


def test_open_connection_with_retry_exhausts_to_structured_error():
    async def main():
        import socket as socket_mod

        probe = socket_mod.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()
        policy = BackoffPolicy(base=0.001, cap=0.002, max_retries=2, seed=0)
        with pytest.raises(ProtocolError) as ei:
            await open_connection_with_retry("127.0.0.1", port, policy)
        assert "failed after 3 attempt(s)" in str(ei.value)
        assert isinstance(ei.value.cause, OSError)

    asyncio.run(asyncio.wait_for(main(), 30))


def test_send_over_async_stall_timeout_fails_structured():
    """A peer that never reads must fail the sender with a structured
    error within stall_timeout — not park the task forever."""
    async def main():
        import socket as socket_mod

        a, b = socket_mod.socketpair()
        a.setblocking(False)
        b.setblocking(False)
        # shrink the window so a modest payload wedges drain
        a.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_SNDBUF, 8192)
        _, writer = await asyncio.open_connection(sock=a)
        writer.transport.set_write_buffer_limits(high=4096, low=1024)
        e = protocol.encode()
        errs = []
        e.on_error(errs.append)
        ws = e.blob(1 << 20)
        ws.write(b"x" * (1 << 20))
        ws.end()
        e.finalize()
        await asyncio.wait_for(
            send_over_async(e, writer, stall_timeout=0.3), 20)
        assert e.destroyed
        assert any(isinstance(x, ProtocolError) and "stalled" in str(x)
                   for x in errs)
        writer.transport.abort()
        writer.close()
        for s in (a, b):
            s.close()

    asyncio.run(asyncio.wait_for(main(), 30))


# -- FaultyWriter ------------------------------------------------------------

def test_faulty_writer_resegments_flips_and_drops():
    from dat_replication_protocol_tpu.session.faults import (
        FaultPlan,
        FaultyWriter,
        TransportFault,
    )

    sink = []
    w = FaultyWriter(sink.append, FaultPlan(seed=1, max_segment=3,
                                            flip_at=4, flip_mask=0x01))
    w.write(b"\x00" * 10)
    out = b"".join(sink)
    assert len(out) == 10 and max(len(c) for c in sink) <= 3
    assert out[4] == 0x01 and out.count(0) == 9  # exactly one byte flipped

    dead = FaultyWriter(sink.append, FaultPlan(seed=2, drop_at=5))
    with pytest.raises(TransportFault) as ei:
        dead.write(b"x" * 16)
    assert ei.value.offset == 5
    with pytest.raises(TransportFault):
        dead.write(b"more")  # the connection stays dead


# -- review fixes ------------------------------------------------------------

def test_run_resumable_retries_plain_oserror_from_real_sockets():
    """A source backed by a real socket raises ConnectionResetError (not
    TransportFault); the driver must take the reconnect path for it."""
    from dat_replication_protocol_tpu.session.faults import bytes_reader
    from dat_replication_protocol_tpu.session.reconnect import run_resumable

    e = protocol.encode()
    e.change({"key": "k", "change": 1, "from": 0, "to": 1})
    e.finalize()
    wire = e.read()

    class ResettingReader:
        def __init__(self, data, die):
            self._read = bytes_reader(data)
            self._die = die
            self._delivered = 0

        def read(self, n):
            if self._die and self._delivered >= 4:
                raise ConnectionResetError("peer reset")
            out = self._read(min(n, 4))
            self._delivered += len(out)
            return out

    def source(ckpt, failures):
        return ResettingReader(wire[ckpt.wire_offset:], die=(failures == 0))

    d = protocol.decode()
    got = []
    d.change(lambda c, done: (got.append(c.key), done()))
    stats = run_resumable(source, d,
                          BackoffPolicy(base=0.0001, max_retries=2, seed=0),
                          expected_total=len(wire), stall_timeout=5)
    assert stats["reconnects"] == 1 and "peer reset" in stats["faults"][0]
    assert got == ["k"] and d.finished


def test_attach_journal_after_reads_aligns_absolute_offsets():
    e = protocol.encode()
    e.change({"key": "early", "change": 1, "from": 0, "to": 1})
    head = e.read()  # emitted BEFORE the journal attaches
    j = WireJournal()
    e.attach_journal(j)
    assert j.start == len(head)  # window starts past the lost bytes
    e.change({"key": "late", "change": 2, "from": 1, "to": 2})
    e.finalize()
    tail = e.read()
    assert j.read_from(len(head)) == tail  # absolute offsets line up
    with pytest.raises(ResumeError):
        j.read_from(0)  # pre-attach bytes are honestly unrecoverable

    # a journal that cannot seek refuses a late attach instead of
    # silently misaligning
    e2 = protocol.encode()
    e2.change({"key": "x", "change": 1, "from": 0, "to": 1})
    e2.read()
    with pytest.raises(RuntimeError, match="cannot seek"):
        e2.attach_journal([])  # bare list: append() but no seek()


def test_app_handler_oserror_is_not_a_transport_fault():
    """An app callback raising OSError during delivery (ENOSPC while
    materializing a blob, say) must surface raw — retrying it as a
    'transport fault' would resume a stream the failed delivery
    desynchronized and bury the app's real error."""
    from dat_replication_protocol_tpu.session.faults import bytes_reader
    from dat_replication_protocol_tpu.session.reconnect import run_resumable

    e = protocol.encode()
    for i in range(3):
        e.change({"key": f"k{i}", "change": i, "from": i, "to": i + 1})
    e.finalize()
    wire = e.read()

    class R:
        def __init__(self, data):
            self._read = bytes_reader(data)

        def read(self, n):
            return self._read(n)

    d = protocol.decode()
    d.change(lambda c, done: (_ for _ in ()).throw(OSError("ENOSPC: disk full")))
    attempts = []

    def source(ckpt, failures):
        attempts.append(failures)
        return R(wire[ckpt.wire_offset:])

    with pytest.raises(OSError, match="ENOSPC"):
        run_resumable(source, d,
                      BackoffPolicy(base=0.0001, max_retries=5, seed=0),
                      expected_total=len(wire), stall_timeout=5)
    assert attempts == [0]  # no reconnect was attempted for an app error
