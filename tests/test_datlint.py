"""datlint rule engine: one known-bad and one known-good fixture per
rule, each distilled from the real incident that motivated the rule
(ANALYSIS.md maps rules to ADVICE.md findings), plus the suppression
syntax and the CLI contract the tier-1 gate relies on.

The fixtures are deliberately minimal re-creations of the PRE-fix repo
patterns: if a rule stops firing on its bad fixture, the analyzer has
lost the ability to catch the bug class that motivated it.
"""

import textwrap

import pytest

from dat_replication_protocol_tpu.analysis import run_paths
from dat_replication_protocol_tpu.analysis.__main__ import main as datlint_main


def _lint(tmp_path, *files, rules=None):
    """Write {name: source} pairs into tmp_path and lint the directory."""
    for name, source in files:
        (tmp_path / name).write_text(textwrap.dedent(source))
    return run_paths([tmp_path], rules=rules)


def _rules_fired(findings):
    return {f.rule for f in findings}


# -- cursor-coherence (ADVICE.md round 5, high: bulk cursor desync) ---------

# the pre-fix shape of _dispatch_changes_fast: locals advance together,
# but the finally writes back only half the coupled cursor
CURSOR_BAD = '''
# datlint: coupled-state st["f"], st["row"]

def dispatch(st, frames, rows, deliver):
    f = st["f"]
    row = st["row"]
    try:
        while f < len(frames):
            payload = frames[f]
            row += 1
            f += 1
            deliver(payload, rows[row - 1])
    finally:
        st["row"] = row
'''

CURSOR_GOOD = '''
# datlint: coupled-state st["f"], st["row"]

def dispatch(st, frames, rows, deliver):
    f = st["f"]
    row = st["row"]
    try:
        while f < len(frames):
            payload = frames[f]
            row += 1
            f += 1
            deliver(payload, rows[row - 1])
    finally:
        st["f"] = f
        st["row"] = row
'''


def test_cursor_coherence_fires_on_half_writeback(tmp_path):
    findings = _lint(tmp_path, ("desync.py", CURSOR_BAD))
    assert "cursor-coherence" in _rules_fired(findings)
    # both shapes are reported: the subset finally AND the absence of
    # any finally covering the full set
    msgs = [f.message for f in findings if f.rule == "cursor-coherence"]
    # canonical form uses single quotes (ast.unparse)
    assert any("st['f']" in m and "not" in m for m in msgs)


def test_cursor_coherence_fires_on_no_finally_at_all(tmp_path):
    findings = _lint(tmp_path, ("bare.py", '''
        # datlint: coupled-state st["f"], st["row"]

        def advance(st):
            st["row"] += 1
            st["f"] += 1
    '''))
    assert "cursor-coherence" in _rules_fired(findings)


def test_cursor_coherence_clean_on_atomic_writeback(tmp_path):
    assert _lint(tmp_path, ("atomic.py", CURSOR_GOOD)) == []


def test_cursor_coherence_ignores_undeclared_modules(tmp_path):
    # no coupled-state declaration: the rule constrains nothing
    source = CURSOR_BAD.replace("# datlint: coupled-state", "# not-a-decl")
    assert _lint(tmp_path, ("free.py", source)) == []


def test_cursor_coherence_malformed_declaration_is_a_finding(tmp_path):
    """A declaration the rule cannot honor must FAIL datlint, not turn
    the rule off while the run still reports clean (dropping the comma
    would otherwise ship the exact half-write-back regression green)."""
    source = CURSOR_BAD.replace('st["f"], st["row"]', 'st["f"] st["row"]')
    findings = _lint(tmp_path, ("desync.py", source))
    msgs = [f.message for f in findings if f.rule == "cursor-coherence"]
    assert any("unparsable member" in m for m in msgs), findings


def test_cursor_coherence_single_member_declaration_is_a_finding(tmp_path):
    # one member is not a coupling; silently ignoring it disables the rule
    source = CURSOR_BAD.replace('st["f"], st["row"]', 'st["row"]')
    findings = _lint(tmp_path, ("desync.py", source))
    msgs = [f.message for f in findings if f.rule == "cursor-coherence"]
    assert any("at least two" in m for m in msgs), findings


# -- env-cache-policy (ADVICE.md round 5, low: DISABLE split-brain) ---------

# the pre-fix change_codec._fastpath_mod: the env decision is frozen
# into the module cache on first call
ENV_BAD_FN = '''
import os

_cache = None
_tried = False


def get():
    global _cache, _tried
    if not _tried:
        _tried = True
        if os.environ.get("DAT_FASTPATH_DISABLE"):
            _cache = None
        else:
            _cache = object()
    return _cache
'''

ENV_GOOD = '''
import os

_cache = None
_tried = False


def get():
    if os.environ.get("DAT_FASTPATH_DISABLE"):
        return None
    return _load_once()


def _load_once():
    global _cache, _tried
    if not _tried:
        _tried = True
        _cache = object()
    return _cache
'''


def test_env_cache_fires_on_frozen_function_cache(tmp_path):
    findings = _lint(tmp_path, ("frozen.py", ENV_BAD_FN))
    assert _rules_fired(findings) == {"env-cache-policy"}


def test_env_cache_fires_on_module_level_env_read(tmp_path):
    findings = _lint(tmp_path, ("modlevel.py", '''
        import os

        FASTPATH_OFF = os.environ.get("DAT_FASTPATH_DISABLE")
    '''))
    assert _rules_fired(findings) == {"env-cache-policy"}


def test_env_cache_clean_on_per_call_read(tmp_path):
    assert _lint(tmp_path, ("shared.py", ENV_GOOD)) == []


# -- unbounded-join (ADVICE.md round 5, low: sidecar drain hang) ------------

JOIN_BAD = '''
def run_session(sender, sock):
    sock.settimeout(None)
    sender.join()
'''

JOIN_GOOD = '''
def run_session(sender, sock, parts):
    sock.settimeout(30.0)
    while sender.is_alive():
        sender.join(timeout=0.25)
    return ", ".join(parts)
'''


def test_unbounded_join_fires_on_bare_join_and_settimeout_none(tmp_path):
    findings = _lint(tmp_path, ("hang.py", JOIN_BAD))
    assert [f.rule for f in findings] == ["unbounded-join"] * 2


def test_unbounded_join_clean_on_bounded_waits(tmp_path):
    # str.join with an argument must NOT be confused with Thread.join
    assert _lint(tmp_path, ("bounded.py", JOIN_GOOD)) == []


# -- bounded-wait (ISSUE 2: lost-wakeup hangs; aio's bare awaits) -----------

# the pre-fix shape of aio.send_over_async: an idle encoder whose
# producer dies parks the pump task forever in wait(); a peer that
# stops reading parks it forever in drain()
WAIT_BAD = '''
async def pump(encoder, readable, writer):
    while True:
        data = encoder.read(65536)
        if not data:
            await readable.wait()
            continue
        writer.write(data)
        await writer.drain()
'''

WAIT_GOOD = '''
import asyncio


async def pump(encoder, readable, writer):
    while True:
        data = encoder.read(65536)
        if not data:
            await asyncio.wait_for(readable.wait(), 0.5)
            continue
        writer.write(data)
        await asyncio.wait_for(writer.drain(), 30.0)


def threaded_pump(event):
    while not event.wait(0.5):
        pass
'''


def test_bounded_wait_fires_on_bare_wait_and_drain(tmp_path):
    findings = _lint(tmp_path, ("hangs.py", WAIT_BAD))
    waits = [f for f in findings if f.rule == "bounded-wait"]
    assert len(waits) == 2
    joined = " ".join(f.message for f in waits)
    assert ".wait()" in joined and ".drain()" in joined


def test_bounded_wait_clean_on_wait_for_and_timeouts(tmp_path):
    assert _lint(tmp_path, ("bounded.py", WAIT_GOOD)) == []


def test_bounded_wait_allow_marker_is_the_escape_hatch(tmp_path):
    findings = _lint(tmp_path, ("justified.py", '''
        async def pump(writer, event):
            # datlint: allow-unbounded-wait -- peer trusted, see docstring
            await writer.drain()
            await event.wait()  # datlint: allow-unbounded-wait -- same
    '''))
    assert findings == []


def test_bounded_wait_does_not_double_report_join(tmp_path):
    # .join() belongs to unbounded-join; one finding, not two
    findings = _lint(tmp_path, ("joins.py", JOIN_BAD))
    assert "bounded-wait" not in _rules_fired(findings)


# -- jit-purity (PERF.md: host effects inside traced bodies) ----------------

JIT_BAD = '''
import os

import jax
import numpy as np


@jax.jit
def step(x):
    if os.environ.get("DAT_DEBUG"):
        x = x + 1
    return x


def kernel(x, out):
    host = np.asarray(x)
    out.block_until_ready()
    return host


traced = jax.jit(kernel)
'''

JIT_GOOD = '''
import os

import jax
import jax.numpy as jnp

DEBUG = bool(os.environ.get("DAT_DEBUG"))  # datlint: disable=env-cache-policy -- fixture: frozen on purpose


@jax.jit
def step(x):
    return jnp.sum(x * 2)


def host_helper(x):
    # not traced: environment reads and host syncs are fine here
    if os.environ.get("DAT_DEBUG"):
        x.block_until_ready()
    return x
'''


def test_jit_purity_fires_on_env_read_sync_and_materialize(tmp_path):
    findings = _lint(tmp_path, ("impure.py", JIT_BAD))
    impure = [f for f in findings if f.rule == "jit-purity"]
    joined = " ".join(f.message for f in impure)
    assert "os.environ" in joined          # frozen trace-time env read
    assert "block_until_ready" in joined   # host sync point
    assert "np.asarray" in joined          # device->host transfer
    assert len(impure) == 3


def test_jit_purity_clean_on_pure_traced_body(tmp_path):
    assert _lint(tmp_path, ("pure.py", JIT_GOOD)) == []


# -- wire-constant-parity (cross-implementation constant drift) -------------

WIRE_PY = '''
MAX_VARINT_LEN = 10
MAX_HEADER_LEN = MAX_VARINT_LEN + 1

TYPE_HEADER = 0
TYPE_CHANGE = 1
TYPE_BLOB = 2
'''

WIRE_C_GOOD = '''
enum FrameType {
  TYPE_HEADER = 0,
  TYPE_CHANGE = 1,
  TYPE_BLOB = 2,
};
// wire: MAX_VARINT_LEN = 10
#define MAX_HEADER_LEN 11
'''

# a drifted C copy: TYPE_BLOB renumbered, the varint cap widened
WIRE_C_BAD = WIRE_C_GOOD.replace("TYPE_BLOB = 2", "TYPE_BLOB = 3").replace(
    "MAX_VARINT_LEN = 10", "MAX_VARINT_LEN = 12")


def test_wire_parity_fires_on_cross_language_drift(tmp_path):
    findings = _lint(tmp_path, ("consts.py", WIRE_PY),
                     ("native.cpp", WIRE_C_BAD))
    drift = [f for f in findings if f.rule == "wire-constant-parity"]
    assert {m.split("wire constant ")[1].split(" ")[0] for m in
            (f.message for f in drift)} == {"TYPE_BLOB", "MAX_VARINT_LEN"}


def test_wire_parity_clean_when_constants_agree(tmp_path):
    # includes the folded MAX_HEADER_LEN = MAX_VARINT_LEN + 1 == 11
    assert _lint(tmp_path, ("consts.py", WIRE_PY),
                 ("native.cpp", WIRE_C_GOOD)) == []


def test_wire_parity_fires_on_python_python_drift(tmp_path):
    findings = _lint(tmp_path, ("a.py", "TYPE_CHANGE = 1\n"),
                     ("b.py", "_TYPE_CHANGE = 7\n"))  # underscore-stripped
    assert _rules_fired(findings) == {"wire-constant-parity"}


def test_wire_parity_single_site_constrains_nothing(tmp_path):
    assert _lint(tmp_path, ("only.py", "TYPE_CHANGE = 99\n")) == []


# ChangeBatch extension constants: the frame id, the payload version
# byte, and the capability-negotiation bit are all watched — a fork in
# any of them ships a peer that silently stops understanding itself
BATCH_PY = '''
TYPE_CHANGE_BATCH = 3
CAP_CHANGE_BATCH = 1
BATCH_VERSION = 1
'''

BATCH_C_GOOD = '''
// wire: TYPE_CHANGE_BATCH = 3
constexpr int BATCH_VERSION = 1;
'''


def test_wire_parity_covers_change_batch_constants(tmp_path):
    bad = BATCH_C_GOOD.replace("TYPE_CHANGE_BATCH = 3",
                               "TYPE_CHANGE_BATCH = 4").replace(
        "BATCH_VERSION = 1;", "BATCH_VERSION = 2;")
    findings = _lint(tmp_path, ("consts.py", BATCH_PY),
                     ("native.cpp", bad))
    drift = [f for f in findings if f.rule == "wire-constant-parity"]
    assert {m.split("wire constant ")[1].split(" ")[0] for m in
            (f.message for f in drift)} == {"TYPE_CHANGE_BATCH",
                                            "BATCH_VERSION"}


def test_wire_parity_change_batch_clean_when_agreeing(tmp_path):
    assert _lint(tmp_path, ("consts.py", BATCH_PY),
                 ("native.cpp", BATCH_C_GOOD)) == []


def test_wire_parity_cap_constant_python_python_drift(tmp_path):
    findings = _lint(tmp_path, ("a.py", "CAP_CHANGE_BATCH = 1\n"),
                     ("b.py", "CAP_CHANGE_BATCH = 2\n"))
    assert _rules_fired(findings) == {"wire-constant-parity"}


# Gear CDC scramble constants (ISSUE 7): ops/rabin.py and BOTH native
# scan loops (dat_gear_candidates + the fused dat_cdc_hash) write them
# down independently — a fork is a route fork: two "equivalent" engines
# silently cutting different chunks.
GEAR_PY = '''
_GEAR_C1 = 0x9E3779B1
_GEAR_C2 = 0x85EBCA77
'''

GEAR_C_GOOD = '''
// wire: GEAR_C1 = 0x9E3779B1
// wire: GEAR_C2 = 0x85EBCA77
const uint32_t c1 = 0x9E3779B1u, c2 = 0x85EBCA77u;
'''


def test_wire_parity_covers_gear_constants(tmp_path):
    bad = GEAR_C_GOOD.replace("GEAR_C1 = 0x9E3779B1",
                              "GEAR_C1 = 0x9E3779B9")
    findings = _lint(tmp_path, ("rabin.py", GEAR_PY), ("native.cpp", bad))
    drift = [f for f in findings if f.rule == "wire-constant-parity"]
    assert {m.split("wire constant ")[1].split(" ")[0] for m in
            (f.message for f in drift)} == {"GEAR_C1"}


def test_wire_parity_gear_constants_clean_when_agreeing(tmp_path):
    assert _lint(tmp_path, ("rabin.py", GEAR_PY),
                 ("native.cpp", GEAR_C_GOOD)) == []


# Rateless reconciliation constants (ISSUE 10): the negotiation trio
# (frame type / capability bit / payload version) plus the splitmix64
# mapping constants written down independently in ops/rateless.py and
# the native dat_rateless_build engine — a mapping fork is a route fork
# (two engines assigning elements to different coded symbols, a symbol
# stream that silently never decodes).
RECONCILE_PY = '''
TYPE_RECONCILE = 4
CAP_RECONCILE = 2
RECONCILE_VERSION = 1
RATELESS_GAMMA = 0x9E3779B97F4A7C15
RATELESS_MIX1 = 0xBF58476D1CE4E5B9
RATELESS_MIX2 = 0x94D049BB133111EB
'''

RECONCILE_C_GOOD = '''
// wire: TYPE_RECONCILE = 4
// wire: RECONCILE_VERSION = 1
// wire: RATELESS_GAMMA = 0x9E3779B97F4A7C15
// wire: RATELESS_MIX1 = 0xBF58476D1CE4E5B9
// wire: RATELESS_MIX2 = 0x94D049BB133111EB
'''


def test_wire_parity_covers_reconcile_constants(tmp_path):
    bad = RECONCILE_C_GOOD.replace(
        "TYPE_RECONCILE = 4", "TYPE_RECONCILE = 5").replace(
        "RATELESS_GAMMA = 0x9E3779B97F4A7C15",
        "RATELESS_GAMMA = 0x9E3779B97F4A7C16")
    findings = _lint(tmp_path, ("rateless.py", RECONCILE_PY),
                     ("native.cpp", bad))
    drift = [f for f in findings if f.rule == "wire-constant-parity"]
    assert {m.split("wire constant ")[1].split(" ")[0] for m in
            (f.message for f in drift)} == {"TYPE_RECONCILE",
                                            "RATELESS_GAMMA"}


def test_wire_parity_reconcile_constants_clean_when_agreeing(tmp_path):
    assert _lint(tmp_path, ("rateless.py", RECONCILE_PY),
                 ("native.cpp", RECONCILE_C_GOOD)) == []


def test_wire_parity_cap_reconcile_python_python_drift(tmp_path):
    findings = _lint(tmp_path, ("a.py", "CAP_RECONCILE = 2\n"),
                     ("b.py", "CAP_RECONCILE = 4\n"))
    assert _rules_fired(findings) == {"wire-constant-parity"}


def test_obs_discipline_covers_fused_route_telemetry(tmp_path):
    # the single-pass module's counters/engine notes carry the same
    # literal-name contract as every other telemetry site
    findings = _lint(tmp_path, ("fused.py", '''
        def f(_counter, _note_engine, which):
            _counter("cdc.fused." + which).inc()
            _note_engine("cdc.hash", "fused1p-native", bytes=1)
    '''))
    assert sum(f.rule == "obs-discipline" for f in findings) == 1


# -- suppressions -----------------------------------------------------------

def test_line_suppression_silences_one_finding(tmp_path):
    findings = _lint(tmp_path, ("sup.py", '''
        def wait(sender, other):
            sender.join()  # datlint: disable=unbounded-join -- test only
            other.join()
    '''))
    assert len(findings) == 1 and findings[0].rule == "unbounded-join"
    assert findings[0].line == 4  # only the unsuppressed join


def test_comment_line_above_suppresses_the_next_line(tmp_path):
    findings = _lint(tmp_path, ("above.py", '''
        def wait(sender):
            # datlint: disable=unbounded-join -- drained by caller
            sender.join()
    '''))
    assert findings == []


def test_file_suppression_silences_whole_file(tmp_path):
    findings = _lint(tmp_path, ("filewide.py", '''
        # datlint: disable-file=unbounded-join -- fixture: joins audited
        def wait(a, b):
            a.join()
            b.join()
    '''))
    assert findings == []


def test_suppression_in_string_literal_is_inert(tmp_path):
    findings = _lint(tmp_path, ("strlit.py", '''
        DOC = "datlint: disable-file=unbounded-join"

        def wait(sender):
            sender.join()
    '''))
    assert len(findings) == 1


def test_stale_suppression_flags_a_marker_suppressing_nothing(tmp_path):
    findings = _lint(tmp_path, ("stale.py", '''
        def quiet():
            return 1  # datlint: disable=unbounded-join -- long gone
    '''))
    assert [f.rule for f in findings] == ["stale-suppression"]
    assert "zero findings" in findings[0].message
    assert findings[0].line == 3


def test_suppression_without_a_reason_is_a_finding(tmp_path):
    # the suppression WORKS (no unbounded-join finding) but the missing
    # justification is itself reported: audited exceptions carry their
    # why in the same comment
    findings = _lint(tmp_path, ("noreason.py", '''
        def wait(sender):
            sender.join()  # datlint: disable=unbounded-join
    '''))
    assert [f.rule for f in findings] == ["stale-suppression"]
    assert "reason" in findings[0].message


def test_used_and_reasoned_suppression_is_silent(tmp_path):
    findings = _lint(tmp_path, ("used.py", '''
        def wait(sender):
            sender.join()  # datlint: disable=unbounded-join -- drained
    '''))
    assert findings == []


def test_wildcard_suppression_is_not_judged_for_staleness(tmp_path):
    # disable-file=all suppresses ANY rule, so "suppressed zero
    # findings" is not decidable per-rule — never guess; the reason
    # requirement still applies (and is satisfied here)
    findings = _lint(tmp_path, ("wild.py", '''
        # datlint: disable-file=all -- fixture: blanket escape hatch
        def quiet():
            return 1
    '''))
    assert findings == []


def test_stale_audit_skips_rules_that_did_not_run(tmp_path):
    from dat_replication_protocol_tpu.analysis.engine import \
        StaleSuppression

    # unbounded-join is not in this run, so its marker's staleness is
    # unknowable — only the reason requirement is checkable (and met)
    findings = _lint(tmp_path, ("subset.py", '''
        def quiet():
            return 1  # datlint: disable=unbounded-join -- other run
    '''), rules=[StaleSuppression()])
    assert findings == []


def test_c_comment_suppression(tmp_path):
    # two C twins disagreeing on an explicit `// wire:` marker: the
    # finding lands on the FIRST site (a.cpp), where the C-comment
    # suppression must both silence it AND be credited as used (no
    # stale-suppression echo)
    findings = _lint(
        tmp_path,
        ("a.cpp",
         "// wire: TYPE_CHANGE = 1"
         "  // datlint: disable=wire-constant-parity -- fixture drift\n"),
        ("b.cpp", "// wire: TYPE_CHANGE = 2\n"))
    assert findings == []


# -- engine edges -----------------------------------------------------------

def test_unparsable_python_is_a_finding_not_a_skip(tmp_path):
    findings = _lint(tmp_path, ("broken.py", "def f(:\n"))
    assert [f.rule for f in findings] == ["parse-error"]


def test_rule_filter_runs_only_selected_rules(tmp_path):
    findings = _lint(tmp_path, ("both.py", JOIN_BAD + ENV_BAD_FN),
                     rules=None)
    assert _rules_fired(findings) >= {"unbounded-join", "env-cache-policy"}
    from dat_replication_protocol_tpu.analysis import rule_by_name
    only = run_paths([tmp_path], rules=[rule_by_name("unbounded-join")])
    assert _rules_fired(only) == {"unbounded-join"}


# -- CLI contract (what the tier-1 gate and pre-merge hooks rely on) --------

def test_cli_exit_codes(tmp_path, capsys):
    clean = tmp_path / "clean"
    clean.mkdir()
    (clean / "ok.py").write_text("X = 1\n")
    assert datlint_main([str(clean)]) == 0
    assert "clean" in capsys.readouterr().out

    dirty = tmp_path / "dirty"
    dirty.mkdir()
    (dirty / "bad.py").write_text("def f(t):\n    t.join()\n")
    assert datlint_main([str(dirty)]) == 1
    out = capsys.readouterr().out
    assert "unbounded-join" in out and "finding" in out

    assert datlint_main(["--rule", "no-such-rule", str(clean)]) == 2
    assert datlint_main([str(tmp_path / "missing")]) == 2


def test_cli_list_rules_names_all_five(capsys):
    assert datlint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for name in ("cursor-coherence", "env-cache-policy", "unbounded-join",
                 "jit-purity", "wire-constant-parity"):
        assert name in out


def test_findings_are_sorted_and_rendered_with_location(tmp_path):
    findings = _lint(tmp_path, ("zz.py", JOIN_BAD), ("aa.py", JOIN_BAD))
    assert findings == sorted(findings)
    rendered = findings[0].render()
    assert "aa.py" in rendered and "unbounded-join:" in rendered


# -- obs-discipline (ISSUE 3: greppable telemetry names; stdout is wire) ----

OBS_BAD = '''
def instrument(kind, registry, emit):
    c = registry.counter(f"decoder.{kind}")
    c.inc()
    emit("decoder." + kind, offset=0)
    print("decoded a frame")
'''

OBS_GOOD = '''
import sys

def instrument(registry, emit):
    c = registry.counter("decoder.changes")
    c.inc()
    emit("protocol.error", offset=0)
    print("diagnostics", file=sys.stderr)
'''


def test_obs_discipline_fires_on_dynamic_names_and_bare_print(tmp_path):
    findings = _lint(tmp_path, ("dyn.py", OBS_BAD))
    obs = [f for f in findings if f.rule == "obs-discipline"]
    assert len(obs) == 3  # f-string counter, concatenated emit, bare print
    msgs = " ".join(f.message for f in obs)
    assert "non-literal" in msgs and "print" in msgs


def test_obs_discipline_clean_on_literals_and_stderr(tmp_path):
    assert _lint(tmp_path, ("lit.py", OBS_GOOD)) == []


def test_obs_discipline_matches_hoisted_underscore_aliases(tmp_path):
    # the package idiom: `from ..obs.metrics import counter as _counter`
    findings = _lint(tmp_path, ("alias.py", '''
        def instrument(_counter, _emit, name):
            _counter(name).inc()
            _emit(name, x=1)
    '''))
    assert sum(f.rule == "obs-discipline" for f in findings) == 2


def test_obs_discipline_exempts_cli_main_prints(tmp_path):
    # a __main__.py CLI's stdout IS its interface
    main_dir = tmp_path / "somepkg"
    main_dir.mkdir()
    (main_dir / "__main__.py").write_text('print("findings: 0")\n')
    findings = run_paths([tmp_path])
    assert "obs-discipline" not in _rules_fired(findings)


def test_obs_discipline_exempts_the_obs_plumbing_itself(tmp_path):
    # obs/metrics.py forwards `name` params by design — not a site
    obs_dir = tmp_path / "obs"
    obs_dir.mkdir()
    (obs_dir / "metrics.py").write_text(textwrap.dedent('''
        def counter(name):
            return REGISTRY.counter(name)
    '''))
    findings = run_paths([tmp_path])
    assert "obs-discipline" not in _rules_fired(findings)


def test_obs_discipline_suppression(tmp_path):
    findings = _lint(tmp_path, ("sup.py", '''
        def instrument(emit, name):
            emit(name, x=1)  # datlint: disable=obs-discipline
    '''))
    assert "obs-discipline" not in _rules_fired(findings)


# -- obs-discipline: fleet-plane extensions (ISSUE 11) ----------------------

def test_obs_discipline_watermark_role_must_be_literal(tmp_path):
    # the watermark ROLE keys the fleet lag join — same greppability
    # contract as metric names; the LINK argument is runtime by design
    findings = _lint(tmp_path, ("wm.py", '''
        def register(WATERMARKS, role, link, j):
            WATERMARKS.track(role, link, lambda: j.end)
    '''))
    assert sum(f.rule == "obs-discipline" for f in findings) == 1
    findings = _lint(tmp_path, ("wm_ok.py", '''
        def register(WATERMARKS, link, j):
            WATERMARKS.track("append", link, lambda: j.end)
    '''))
    # tmp_path still holds wm.py from above — scope to the literal case
    assert not [f for f in findings if f.path.endswith("wm_ok.py")]


def test_obs_discipline_exempts_fleet_plane_plumbing(tmp_path):
    # obs/watermarks.py renders labeled names from tracked state,
    # obs/fleet.py ships whole snapshots — plumbing, not sites
    obs_dir = tmp_path / "obs"
    obs_dir.mkdir()
    (obs_dir / "watermarks.py").write_text(textwrap.dedent('''
        def _collect(links):
            return {f"session.wire.offset{{link={k}}}": v
                    for k, v in links.items()}

        def track(role, link, fn, registry):
            registry.gauge(role + link)
    '''))
    (obs_dir / "fleet.py").write_text(textwrap.dedent('''
        def join(name, registry):
            return registry.counter(name)
    '''))
    findings = run_paths([tmp_path])
    assert "obs-discipline" not in _rules_fired(findings)


HEALTHZ_LOCK_BAD = '''
def serve_healthz(self):
    with self._lock:
        return {"ok": True, "sessions": len(self._sessions)}
'''

HEALTHZ_DISPATCH_BAD = '''
def default_healthz(pipeline):
    pipeline.flush()
    return {"ok": True}
'''

HEALTHZ_OK = '''
def default_healthz(self, admission_fn):
    adm = admission_fn()
    return {"ok": bool(adm.get("open"))}

def other_route(self):
    with self._lock:  # non-healthz handlers may lock (snapshots do)
        return dict(self._state)
'''


def _lint_obs_http(tmp_path, source):
    obs_dir = tmp_path / "obs"
    obs_dir.mkdir(exist_ok=True)
    (obs_dir / "http.py").write_text(textwrap.dedent(source))
    return run_paths([tmp_path])


def test_healthz_handler_must_not_take_a_lock(tmp_path):
    findings = _lint_obs_http(tmp_path, HEALTHZ_LOCK_BAD)
    obs = [f for f in findings if f.rule == "obs-discipline"]
    assert len(obs) == 1 and "lock-free" in obs[0].message


def test_healthz_handler_must_not_dispatch(tmp_path):
    findings = _lint_obs_http(tmp_path, HEALTHZ_DISPATCH_BAD)
    obs = [f for f in findings if f.rule == "obs-discipline"]
    assert len(obs) == 1 and "device" in obs[0].message


def test_healthz_check_scoped_to_healthz_functions_in_obs_http(tmp_path):
    # locks in NON-healthz functions of obs/http.py are fine, and the
    # same healthz-named code outside obs/http.py is out of scope
    assert "obs-discipline" not in _rules_fired(
        _lint_obs_http(tmp_path, HEALTHZ_OK))
    findings = _lint(tmp_path, ("elsewhere.py", HEALTHZ_LOCK_BAD))
    assert "obs-discipline" not in _rules_fired(findings)


def test_obs_discipline_covers_trace_span_sites(tmp_path):
    # ISSUE 4 satellite: span names carry the same literal-name contract
    # as event names — the timeline CLI and trace viewers key on them
    findings = _lint(tmp_path, ("sp.py", '''
        def f(trace_span, trace_instant, phase):
            with trace_span(phase):
                trace_instant("decoder." + phase, offset=0)
    '''))
    assert sum(f.rule == "obs-discipline" for f in findings) == 2


def test_obs_discipline_clean_on_literal_span_names(tmp_path):
    assert _lint(tmp_path, ("spok.py", '''
        def f(trace_span, trace_instant):
            with trace_span("reconnect.attempt", attempt=1):
                trace_instant("decoder.frame", offset=0)
    ''')) == []


def test_obs_discipline_matches_tracing_receiver_aliases(tmp_path):
    # the package idiom: `from ..obs import tracing as _obs_tracing`
    findings = _lint(tmp_path, ("recv.py", '''
        def f(_obs_tracing, tracing, name):
            _obs_tracing.trace_span(name)
            tracing.trace_instant(name, offset=1)
    '''))
    assert sum(f.rule == "obs-discipline" for f in findings) == 2


def test_obs_discipline_exempts_the_span_plumbing_itself(tmp_path):
    # obs/tracing.py and obs/flight.py forward name params by design
    obs_dir = tmp_path / "obs"
    obs_dir.mkdir()
    (obs_dir / "tracing.py").write_text(textwrap.dedent('''
        def trace_span(name, **fields):
            return _make(name, fields)
    '''))
    findings = run_paths([tmp_path])
    assert "obs-discipline" not in _rules_fired(findings)


def test_obs_discipline_covers_jit_site_registrations(tmp_path):
    # ISSUE 5 satellite: the recompile sentinel's site names carry the
    # same literal-name contract — device.jit.trace events and the
    # sentinel snapshot key on them
    findings = _lint(tmp_path, ("js.py", '''
        def f(jit_site, _jit_site, kernel, name):
            a = jit_site(name, kernel)
            b = _jit_site("ops." + name, kernel)
            return a, b
    '''))
    assert sum(f.rule == "obs-discipline" for f in findings) == 2


def test_obs_discipline_clean_on_literal_jit_site_names(tmp_path):
    assert _lint(tmp_path, ("jsok.py", '''
        def f(jit_site, kernel):
            return jit_site("ops.blake2b.packed", kernel)
    ''')) == []


def test_obs_discipline_matches_device_receiver_aliases(tmp_path):
    # the package idiom: `from ..obs import device as _obs_device`
    findings = _lint(tmp_path, ("devrecv.py", '''
        def f(_obs_device, device, kernel, name):
            _obs_device.jit_site(name, kernel)
            device.emit(name, x=1)
    '''))
    assert sum(f.rule == "obs-discipline" for f in findings) == 2


def test_obs_discipline_exempts_the_device_plumbing_itself(tmp_path):
    # obs/device.py forwards site/component names by design
    obs_dir = tmp_path / "obs"
    obs_dir.mkdir()
    (obs_dir / "device.py").write_text(textwrap.dedent('''
        def jit_site(name, fn):
            return _JitSite(name, fn)
    '''))
    findings = run_paths([tmp_path])
    assert "obs-discipline" not in _rules_fired(findings)


def test_obs_discipline_ignores_unrelated_emit_and_histogram_apis(tmp_path):
    # same method NAMES on non-telemetry receivers: logging handlers,
    # sockets, numpy — none of these touch the obs registry
    findings = _lint(tmp_path, ("other.py", '''
        def f(handler, sock, np, record, event, data, bins):
            handler.emit(record)
            sock.emit(event, data)
            np.histogram(data, bins)
    '''))
    assert "obs-discipline" not in _rules_fired(findings)


def test_obs_discipline_covers_loopprof_phase_accounting(tmp_path):
    # ISSUE 18: phase names key the edge.turn.* histogram family, the
    # turn-span fields, and loopdoctor's attribution — same greppable
    # contract as metric names
    findings = _lint(tmp_path, ("lp.py", '''
        def f(prof, profiler, which, sess, dt, n):
            prof.phase(which, dt)
            profiler.account("over" + which, sess.key, dt, n)
    '''))
    assert sum(f.rule == "obs-discipline" for f in findings) == 2


def test_obs_discipline_clean_on_literal_loopprof_phases(tmp_path):
    # the SESSION argument of account() is runtime by design (a
    # collector label, like a watermark LINK) — only the PHASE is held
    # to the literal contract
    assert _lint(tmp_path, ("lpok.py", '''
        def f(prof, sess, dt, n):
            prof.phase("accept", dt)
            prof.account("read", sess.key, dt, n)
            prof.account("overload-ladder", sess.key, dt, 0)
    ''')) == []


def test_obs_discipline_ignores_unrelated_phase_apis(tmp_path):
    # `phase`/`account` on non-telemetry receivers: a state machine's
    # phase setter, a billing API — out of scope
    findings = _lint(tmp_path, ("phother.py", '''
        def f(machine, billing, next_phase, user, amount):
            machine.phase(next_phase)
            billing.account(user, amount)
    '''))
    assert "obs-discipline" not in _rules_fired(findings)


def test_obs_discipline_exempts_the_loopprof_plumbing_itself(tmp_path):
    # obs/loopprof.py accumulates forwarded phase names by design —
    # the greppable literals live at the edge-loop call sites
    obs_dir = tmp_path / "obs"
    obs_dir.mkdir()
    (obs_dir / "loopprof.py").write_text(textwrap.dedent('''
        def account(prof, name, session, seconds, nbytes):
            prof.phase(name, seconds)
    '''))
    findings = run_paths([tmp_path])
    assert "obs-discipline" not in _rules_fired(findings)


def test_obs_discipline_exempts_the_propagation_plumbing_itself(tmp_path):
    # obs/propagation.py (ISSUE 19) renders labeled divergence gauge
    # names from board state and forwards event payloads — plumbing;
    # the greppable `gossip.*` literals live at its own call sites
    obs_dir = tmp_path / "obs"
    obs_dir.mkdir()
    (obs_dir / "propagation.py").write_text(textwrap.dedent('''
        def _collect(links):
            return {f"cluster.divergence{{replica={r},peer={p}}}": v
                    for (r, p), v in links.items()}

        def record_exchange(board, emit, name, **fields):
            emit(name, **fields)
    '''))
    findings = run_paths([tmp_path])
    assert "obs-discipline" not in _rules_fired(findings)


def test_obs_discipline_still_covers_propagation_call_sites(tmp_path):
    # the exemption is the module, not the plane: a CALLER forwarding
    # a runtime event name still trips the rule
    findings = _lint(tmp_path, ("exchange_site.py", '''
        def lit_exchange(emit, name):
            emit(name, peer="r1")
    '''))
    assert sum(f.rule == "obs-discipline" for f in findings) == 1


def test_obs_discipline_clean_on_literal_wirecost_classes(tmp_path):
    # the wire cost plane (ISSUE 20): the CLASS argument of account()
    # is the greppable vocabulary; the LINK is a collector label,
    # runtime by design (same split as loopprof's phase vs session)
    assert _lint(tmp_path, ("wcok.py", '''
        def f(wirecost, link, payload, framing):
            wirecost.account("change", link, "tx", payload, framing)
            wirecost.account("change_batch", link, "rx", payload, framing)
    ''')) == []


def test_obs_discipline_wirecost_class_must_be_literal(tmp_path):
    # a forwarded class name breaks the grep contract exactly like a
    # forwarded metric name: one finding per call site
    findings = _lint(tmp_path, ("wcbad.py", '''
        def f(wirecost, cls, link, payload, framing):
            wirecost.account(cls, link, "tx", payload, framing)
    '''))
    assert sum(f.rule == "obs-discipline" for f in findings) == 1


def test_obs_discipline_exempts_the_wirecost_plumbing_itself(tmp_path):
    # obs/wirecost.py renders labeled counter names from ledger state
    # and forwards the class through its module-level helpers —
    # plumbing; the greppable class literals live at the choke points
    obs_dir = tmp_path / "obs"
    obs_dir.mkdir()
    (obs_dir / "wirecost.py").write_text(textwrap.dedent('''
        def account(board, cls, link, payload, framing):
            board.account(cls, link, "tx", payload, framing)

        def _collect(links):
            return {f"wire.cost.bytes{{link={l},class={c}}}": v
                    for (l, c), v in links.items()}
    '''))
    findings = run_paths([tmp_path])
    assert "obs-discipline" not in _rules_fired(findings)


# -- hub-isolation (ISSUE 8: the shared-engine structural invariants) -------

# the pre-discipline shape: a device dispatch while the hub lock is
# held — every co-resident session's submit convoys behind the device
HUB_LOCK_BAD = '''
class Hub:
    def turn(self):
        with self._lock:
            batch = self._compose()
            self._pipeline.dispatch()
            self._pipeline.flush()
'''

HUB_LOCK_GOOD = '''
class Hub:
    def turn(self):
        with self._lock:
            batch = self._compose()
        self._pipeline.dispatch()
        self._pipeline.flush()
'''

# per-session state reached around the session-keyed accessor
HUB_ACCESSOR_BAD = '''
class Hub:
    def shed(self, key):
        self._sessions[key].shed = "parked-budget"
'''

HUB_ACCESSOR_GOOD = '''
class Hub:
    def _session_state(self, key):
        return self._sessions[key]

    def shed(self, key):
        self._session_state(key).shed = "parked-budget"
'''


def _lint_hub(tmp_path, name, source):
    hub_dir = tmp_path / "hub"
    hub_dir.mkdir(exist_ok=True)
    (hub_dir / name).write_text(textwrap.dedent(source))
    return run_paths([tmp_path])


def test_hub_isolation_fires_on_dispatch_under_lock(tmp_path):
    findings = _lint_hub(tmp_path, "locked.py", HUB_LOCK_BAD)
    hub = [f for f in findings if f.rule == "hub-isolation"]
    assert len(hub) == 2  # dispatch AND flush under the lock
    assert all("with-lock" in f.message for f in hub)


def test_hub_isolation_clean_on_compose_then_dispatch(tmp_path):
    findings = _lint_hub(tmp_path, "clean.py", HUB_LOCK_GOOD)
    assert "hub-isolation" not in _rules_fired(findings)


def test_hub_isolation_covers_engine_closures_and_device_put(tmp_path):
    # hash_begin()/collect() closures and raw device_put are dispatches
    # too, whatever object they hang off
    findings = _lint_hub(tmp_path, "closures.py", '''
        class Hub:
            def turn(self, jax, engine):
                with self.hub_lock:
                    collect = engine.hash_begin(self.payloads)
                    jax.device_put(self.batch)
                    collect()
    ''')
    hub = [f for f in findings if f.rule == "hub-isolation"]
    assert len(hub) == 3  # hash_begin + device_put + the collect() call


def test_hub_isolation_fires_on_raw_sessions_subscript(tmp_path):
    findings = _lint_hub(tmp_path, "subs.py", HUB_ACCESSOR_BAD)
    hub = [f for f in findings if f.rule == "hub-isolation"]
    assert len(hub) == 1 and "session-keyed accessor" in hub[0].message


def test_hub_isolation_clean_via_accessor(tmp_path):
    findings = _lint_hub(tmp_path, "acc.py", HUB_ACCESSOR_GOOD)
    assert "hub-isolation" not in _rules_fired(findings)


def test_hub_isolation_scoped_to_hub_directories(tmp_path):
    # the same shapes OUTSIDE hub/ are other modules' business
    findings = _lint(tmp_path, ("elsewhere.py", HUB_LOCK_BAD))
    assert "hub-isolation" not in _rules_fired(findings)


def test_hub_isolation_suppression(tmp_path):
    findings = _lint_hub(tmp_path, "sup.py", '''
        class Hub:
            def turn(self):
                with self._lock:
                    # datlint: disable=hub-isolation
                    self._pipeline.flush()
    ''')
    assert "hub-isolation" not in _rules_fired(findings)


# -- fanout-hot-path (ISSUE 9: the O(1)-writer broadcast contract) ----------

# the regression shape: a "small" per-peer notification loop (and a
# per-peer copy) inside publish — every produced byte back to O(peers)
FANOUT_WRITER_BAD = '''
class Server:
    def publish(self, data):
        self.log.append(data)
        for peer in self._peers.values():
            peer.pending += bytes(data)
            peer.notify()
'''

# the shipped shape: append/publish do O(1) bookkeeping; the dispatcher
# owns per-peer iteration
FANOUT_WRITER_GOOD = '''
class Server:
    def publish(self, data):
        self.log.append(data)
        self._marks.append((self.log.end, self.now()))

    def _dispatch_turn(self):
        for peer in self._peers.values():
            self.serve(peer)
'''


def _lint_fanout(tmp_path, name, source):
    fdir = tmp_path / "fanout"
    fdir.mkdir(exist_ok=True)
    (fdir / name).write_text(textwrap.dedent(source))
    return run_paths([tmp_path])


def test_fanout_hot_path_fires_on_per_peer_loop_in_publish(tmp_path):
    findings = _lint_fanout(tmp_path, "loop.py", FANOUT_WRITER_BAD)
    hits = [f for f in findings if f.rule == "fanout-hot-path"]
    # the loop itself, plus the peer-state reaches inside it
    assert hits and any("O(1) in peers" in f.message for f in hits)


def test_fanout_hot_path_clean_on_o1_writer(tmp_path):
    findings = _lint_fanout(tmp_path, "clean.py", FANOUT_WRITER_GOOD)
    assert "fanout-hot-path" not in _rules_fired(findings)


def test_fanout_hot_path_fires_on_peer_state_reach_without_loop(tmp_path):
    findings = _lint_fanout(tmp_path, "reach.py", '''
        class Log:
            def append(self, data):
                self._buf += data
                self._cursors["head"].wake()
    ''')
    hits = [f for f in findings if f.rule == "fanout-hot-path"]
    assert len(hits) == 1
    assert "per-peer state" in hits[0].message


def test_fanout_hot_path_fires_on_comprehension_allocation(tmp_path):
    findings = _lint_fanout(tmp_path, "comp.py", '''
        class Server:
            def publish(self, data):
                self.slabs = [bytes(data) for _ in range(2)]
    ''')
    hits = [f for f in findings if f.rule == "fanout-hot-path"]
    assert hits and "loop" in hits[0].message


def test_fanout_hot_path_scoped_to_fanout_directories(tmp_path):
    # the same shapes OUTSIDE fanout/ are other modules' business
    findings = _lint(tmp_path, ("elsewhere.py", FANOUT_WRITER_BAD))
    assert "fanout-hot-path" not in _rules_fired(findings)


def test_fanout_hot_path_ignores_non_writer_functions(tmp_path):
    findings = _lint_fanout(tmp_path, "dispatcher.py", '''
        class Server:
            def _dispatch_turn(self):
                for key in list(self._peers):
                    self._serve(self._peer_state(key))
    ''')
    assert "fanout-hot-path" not in _rules_fired(findings)


def test_fanout_hot_path_suppression(tmp_path):
    findings = _lint_fanout(tmp_path, "sup.py", '''
        class Server:
            def publish(self, data):
                self.log.append(data)
                # one-shot attach barrier, measured O(1) amortized
                # datlint: disable=fanout-hot-path
                for peer in self._warm_peers:
                    peer.prime()
    ''')
    assert "fanout-hot-path" not in _rules_fired(findings)


# Snapshot bootstrap constants (ISSUE 12): the negotiation trio (frame
# type / capability bit / payload version) plus the weighted-
# participation constants written down independently in ops/rateless.py
# and the native dat_rateless_build_w twin — a participation fork is a
# route fork (two engines mapping the same chunk to different cells, a
# chunk-set reconcile that silently never decodes).
SNAPSHOT_PY = '''
TYPE_SNAPSHOT = 5
CAP_SNAPSHOT = 4
SNAPSHOT_VERSION = 1
RATELESS_W_SHIFT = 12
RATELESS_W_CAP = 8
'''

SNAPSHOT_C_GOOD = '''
// wire: TYPE_SNAPSHOT = 5
// wire: SNAPSHOT_VERSION = 1
// wire: RATELESS_W_SHIFT = 12
// wire: RATELESS_W_CAP = 8
'''


def test_wire_parity_covers_snapshot_constants(tmp_path):
    bad = SNAPSHOT_C_GOOD.replace(
        "TYPE_SNAPSHOT = 5", "TYPE_SNAPSHOT = 6").replace(
        "RATELESS_W_SHIFT = 12", "RATELESS_W_SHIFT = 13")
    findings = _lint(tmp_path, ("snapshot.py", SNAPSHOT_PY),
                     ("native.cpp", bad))
    drift = [f for f in findings if f.rule == "wire-constant-parity"]
    assert {m.split("wire constant ")[1].split(" ")[0] for m in
            (f.message for f in drift)} == {"TYPE_SNAPSHOT",
                                            "RATELESS_W_SHIFT"}


def test_wire_parity_snapshot_constants_clean_when_agreeing(tmp_path):
    assert _lint(tmp_path, ("snapshot.py", SNAPSHOT_PY),
                 ("native.cpp", SNAPSHOT_C_GOOD)) == []


def test_wire_parity_weighted_cap_python_python_drift(tmp_path):
    findings = _lint(tmp_path, ("a.py", "RATELESS_W_CAP = 8\n"),
                     ("b.py", "RATELESS_W_CAP = 9\n"))
    assert _rules_fired(findings) == {"wire-constant-parity"}


# Wire-pump scanner constants (ISSUE 14): the native pump shares
# dat_split_frames itself (one scanner — no framing fork by
# construction), but its receive entry restates the header-capacity
# floor as a `// wire:` marker (a slab smaller than one maximal header
# could never make progress at a frame boundary).  The pump-parity
# fixture: a scanner fork is a route fork — a pump-side framing
# constant drifting from wire/framing.py must be a finding, so the
# Python reference pump cannot drift silently behind the native one.
PUMP_PY = '''
MAX_VARINT_LEN = 10
MAX_HEADER_LEN = MAX_VARINT_LEN + 1
'''

PUMP_C_GOOD = '''
// the pump's minimum slab capacity:  // wire: MAX_HEADER_LEN = 11
if (cap < 11 || slice < 1) return DAT_ERR_CAPACITY;
'''


def test_wire_parity_covers_pump_scanner_constant(tmp_path):
    bad = PUMP_C_GOOD.replace("MAX_HEADER_LEN = 11",
                              "MAX_HEADER_LEN = 12")
    findings = _lint(tmp_path, ("framing.py", PUMP_PY),
                     ("native.cpp", bad))
    drift = [f for f in findings if f.rule == "wire-constant-parity"]
    assert {m.split("wire constant ")[1].split(" ")[0] for m in
            (f.message for f in drift)} == {"MAX_HEADER_LEN"}


def test_wire_parity_pump_scanner_clean_when_agreeing(tmp_path):
    assert _lint(tmp_path, ("framing.py", PUMP_PY),
                 ("native.cpp", PUMP_C_GOOD)) == []


# -- structured-error-parity (ISSUE 15: cluster errors carry context) -------

# the pre-contract shape: an error type naming neither the peer nor the
# wire coordinates — a byzantine post-mortem reduced to "something
# failed somewhere"
STRUCTERR_BAD = '''
class GossipBroken(RuntimeError):
    def __init__(self, message):
        super().__init__(message)
'''

STRUCTERR_GOOD = '''
class GossipBroken(RuntimeError):
    def __init__(self, message, *, peer, frame=None, offset=None):
        super().__init__(message)
        self.peer = peer
        self.frame = frame
        self.offset = offset
'''


def _lint_cluster(tmp_path, source, rules=("structured-error-parity",)):
    from dat_replication_protocol_tpu.analysis.rules import ALL_RULES

    pkg = tmp_path / "cluster"
    pkg.mkdir(exist_ok=True)
    (pkg / "err.py").write_text(textwrap.dedent(source))
    return run_paths([tmp_path],
                     rules=[r for r in ALL_RULES if r.name in rules])


def test_structured_error_parity_fires_on_bare_error(tmp_path):
    findings = _lint_cluster(tmp_path, STRUCTERR_BAD)
    assert _rules_fired(findings) == {"structured-error-parity"}
    assert "peer" in findings[0].message


def test_structured_error_parity_fires_on_missing_init(tmp_path):
    findings = _lint_cluster(tmp_path, '''
class GossipBroken(RuntimeError):
    pass
''')
    assert _rules_fired(findings) == {"structured-error-parity"}
    assert "__init__" in findings[0].message


def test_structured_error_parity_clean_on_full_context(tmp_path):
    assert _lint_cluster(tmp_path, STRUCTERR_GOOD) == []


def test_structured_error_parity_accepts_self_assignments(tmp_path):
    # offset/frame may be explicit self assignments instead of
    # pass-through parameters
    assert _lint_cluster(tmp_path, '''
class GossipBroken(Exception):
    def __init__(self, peer):
        super().__init__(peer)
        self.peer = peer
        self.offset = 0
        self.frame = None
''') == []


def test_structured_error_parity_scoped_to_cluster_dirs(tmp_path):
    # the same bare error OUTSIDE a cluster/ directory is not this
    # rule's business
    (tmp_path / "other.py").write_text(textwrap.dedent(STRUCTERR_BAD))
    findings = _lint(tmp_path, ("other.py", STRUCTERR_BAD),
                     rules=None)
    assert "structured-error-parity" not in _rules_fired(findings)


def test_structured_error_parity_suppressible(tmp_path):
    src = STRUCTERR_BAD.replace(
        "class GossipBroken(RuntimeError):",
        "class GossipBroken(RuntimeError):  "
        "# datlint: disable=structured-error-parity")
    assert _lint_cluster(tmp_path, src) == []


def test_structured_error_parity_non_error_classes_exempt(tmp_path):
    assert _lint_cluster(tmp_path, '''
class ReplicaThing:
    def __init__(self):
        self.x = 1
''') == []
