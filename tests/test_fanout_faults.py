"""Chaos isolation proof for the fan-out (ISSUE 9 acceptance): one
misbehaving peer cannot hurt the broadcast.

The sweep serves 8 downstream peers per seed from ONE FanoutServer;
exactly one peer — :meth:`FaultPlan.faulty_session` (the PR 8
per-session scenario axis, reused as the per-peer axis) — misbehaves
per the seed's scenario, the rest consume with benign delivery jitter.
Scenario mapping onto the peer world:

* ``stall``    -> the peer stops accepting bytes at the plan's stall
  coordinate (the client that went away without closing) — shed
  ``stall`` once it makes no progress for the server's stall timeout;
* ``truncate`` -> the peer's transport dies at the plan's truncate
  coordinate (EPIPE mid-writev) — shed ``disconnect``;
* ``flip``     -> the peer acks bytes it was never sent at the plan's
  flip coordinate (a corrupt/hostile ack stream) — shed ``byzantine``.

The contract: every healthy peer receives the wire BYTE-EXACTLY, its
p99 frame latency stays flat (the faulty peer never convoys the
dispatch), and the faulty peer is shed with ONE structured
:class:`PeerShed` whose reason matches the injected scenario — the
oracle cross-checks ``fanout.shed`` events against the predicted
ground truth.  Tier-1 sweeps seeds 0..19; the ``slow`` soak covers 100
more.
"""

from __future__ import annotations

import threading
import time

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.fanout import FanoutServer, PeerShed
from dat_replication_protocol_tpu.session.faults import FaultPlan

N_PEERS = 8
HARD_TIMEOUT = 20.0
# healthy peers' p99 append->delivery latency must stay flat while the
# faulty peer misbehaves; generous vs the ~1ms typical value so shared
# CI boxes never flake, still far below any convoying regime
P99_BUDGET_MS = 500.0

_SCENARIO_TO_SHED = {"stall": "stall", "truncate": "disconnect",
                     "flip": "byzantine"}


def _build_wire() -> bytes:
    e = protocol.encode()
    for j in range(64):
        e.change({"key": f"k{j}", "change": j, "from": j, "to": j + 1,
                  "value": bytes([(j * 17 + t) % 251 for t in range(48)])})
    b = e.blob(4096)
    b.write(bytes(k % 241 for k in range(4096)))
    b.end()
    e.finalize()
    parts = []
    while True:
        d = e.read(4096)
        if d is None:
            break
        parts.append(d)
    return b"".join(parts)


WIRE = _build_wire()


class _HealthySink:
    """Benign delivery jitter from the peer's plan: accepts bounded
    bites (re-segmentation) but always makes progress."""

    def __init__(self, plan: FaultPlan):
        self.buf = bytearray()
        self._bite = plan.max_segment or (1 << 20)

    def __call__(self, views) -> int:
        n = 0
        budget = max(512, self._bite)  # tiny bites still progress
        for v in views:
            take = min(len(v), budget - n)
            self.buf.extend(bytes(v[:take]))
            n += take
            if n >= budget:
                break
        return n


class _FaultySink:
    """The faulty peer's transport, driven by the plan's coordinates:
    stalls forever at ``stall_at`` or dies with OSError at
    ``truncate_at`` (byzantine acks are driven from the test thread).
    The coordinate is enforced WITHIN a call — a single writev burst
    can cover the whole wire, so an entry-only check would skip it."""

    def __init__(self, stall_at=None, die_at=None):
        self.buf = bytearray()
        self._stall_at = stall_at
        self._die_at = die_at

    def __call__(self, views) -> int:
        fault_at = self._stall_at if self._stall_at is not None \
            else self._die_at
        if fault_at is None:  # byzantine peers consume normally; the
            fault_at = 1 << 60  # fault is in their ACK stream
        budget = fault_at - len(self.buf)
        if budget <= 0:
            if self._die_at is not None:
                raise OSError(32, "Broken pipe (injected)")
            return 0  # stalled for good: the shed scan's business now
        n = 0
        for v in views:
            take = min(len(v), budget - n)
            self.buf.extend(bytes(v[:take]))
            n += take
            if n >= budget:
                break
        return n


def _run_fanout_seed(seed: int):
    """One sweep seed: 8 peers, one faulted per the seed's scenario.
    Returns (peers, sinks, faulty index, scenario, shed reason)."""
    faulty = FaultPlan.faulty_session(seed, N_PEERS)
    scenario = FaultPlan.session_scenario(seed, N_PEERS)
    srv = FanoutServer(stall_timeout=0.15, retention_budget=1 << 24)
    peers = {}
    sinks = {}
    byz_driver = None
    try:
        for i in range(N_PEERS):
            plan = FaultPlan.for_sweep(seed, len(WIRE), attempt=0,
                                       session=i, n_sessions=N_PEERS)
            if i != faulty:
                sinks[i] = _HealthySink(plan)
                peers[i] = srv.attach_peer(f"seed{seed}-p{i}",
                                           sink=sinks[i])
            elif scenario == "stall":
                sinks[i] = _FaultySink(stall_at=plan.stall_at)
                peers[i] = srv.attach_peer(f"seed{seed}-p{i}",
                                           sink=sinks[i])
            elif scenario == "truncate":
                sinks[i] = _FaultySink(die_at=plan.truncate_at)
                peers[i] = srv.attach_peer(f"seed{seed}-p{i}",
                                           sink=sinks[i])
            else:  # flip -> byzantine acks, driven from a thread
                sinks[i] = _FaultySink()
                peers[i] = srv.attach_peer(f"seed{seed}-p{i}",
                                           sink=sinks[i],
                                           explicit_ack=True)

                def _drive_byzantine(p=peers[i], at=plan.flip_at):
                    deadline = time.monotonic() + HARD_TIMEOUT / 2
                    while p.sent < at and p.shed_reason is None \
                            and time.monotonic() < deadline:
                        time.sleep(0.005)
                    try:
                        p.ack(p.sent + 1 + (plan.flip_mask or 1))
                    except PeerShed:
                        pass  # the structured shed IS the expectation

                byz_driver = threading.Thread(target=_drive_byzantine,
                                              daemon=True)
                byz_driver.start()

        for off in range(0, len(WIRE), 1024):
            srv.publish(WIRE[off:off + 1024])
        srv.seal()

        deadline = time.monotonic() + HARD_TIMEOUT
        for i in range(N_PEERS):
            if i == faulty:
                continue
            assert peers[i].wait_done(max(0.1, deadline - time.monotonic())), \
                f"seed {seed}: healthy peer {i} never finished"
        while peers[faulty].shed_reason is None \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        if byz_driver is not None:
            byz_driver.join(5)
        stats = {i: peers[i].stats() for i in range(N_PEERS)}
        return sinks, stats, faulty, scenario
    finally:
        srv.close()


@pytest.mark.parametrize("seed", range(20))
def test_sweep_one_faulty_peer_cannot_hurt_the_broadcast(seed, obs_enabled):
    """The acceptance sweep: 8 peers, one faulted, healthy delivery
    byte-exact with flat p99, the faulty peer shed with the predicted
    structured reason — oracle-checked against fanout.shed events."""
    from dat_replication_protocol_tpu.obs.events import EVENTS

    sinks, stats, faulty, scenario = _run_fanout_seed(seed)

    for i in range(N_PEERS):
        if i == faulty:
            continue
        assert bytes(sinks[i].buf) == WIRE, \
            f"seed {seed}: healthy peer {i} bytes diverged"
        assert stats[i]["shed"] is None and stats[i]["done"]
        p99 = stats[i]["lat_p99_ms"]
        assert p99 is not None and p99 < P99_BUDGET_MS, \
            f"seed {seed}: healthy peer {i} p99 {p99}ms"

    expected = _SCENARIO_TO_SHED[scenario]
    assert stats[faulty]["shed"] == expected, \
        f"seed {seed}: scenario {scenario} -> {stats[faulty]['shed']}"

    # oracle: every fanout.shed event names ONLY the faulty peer, with
    # the predicted reason
    sheds = EVENTS.events("fanout.shed")
    assert sheds, f"seed {seed}: no fanout.shed event recorded"
    for ev in sheds:
        assert ev["fields"]["key"] == f"seed{seed}-p{faulty}"
        assert ev["fields"]["reason"] == expected


@pytest.mark.slow
def test_sweep_soak_100_seeds():
    for seed in range(20, 120):
        sinks, stats, faulty, scenario = _run_fanout_seed(seed)
        for i in range(N_PEERS):
            if i == faulty:
                continue
            assert bytes(sinks[i].buf) == WIRE, \
                f"seed {seed} peer {i} diverged"
            assert stats[i]["done"] and stats[i]["shed"] is None
        assert stats[faulty]["shed"] == _SCENARIO_TO_SHED[scenario], \
            f"seed {seed}: {scenario} -> {stats[faulty]['shed']}"


# -- targeted isolation arms --------------------------------------------------


def test_three_second_stall_leaves_healthy_p99_flat():
    """The acceptance arm, measured: one peer stalls for 3 s mid-wire
    (below the shed timeout, so it is window-bounded, not shed); the
    7 healthy peers finish long before the stall ends with flat p99,
    and the staller still completes byte-exactly afterwards."""
    srv = FanoutServer(stall_timeout=10.0, retention_budget=1 << 24)
    try:
        gate_t = [None]
        stalled = bytearray()

        def stall_sink(views):
            # accept only up to the half-way coordinate, then stall 3 s
            # (enforced in-call: one burst can cover the whole wire)
            if gate_t[0] is None:
                gate_t[0] = time.monotonic() + 3.0
            if time.monotonic() < gate_t[0]:
                budget = len(WIRE) // 2 - len(stalled)
                if budget <= 0:
                    return 0
            else:
                budget = 1 << 30
            n = 0
            for v in views:
                take = min(len(v), budget - n)
                stalled.extend(bytes(v[:take]))
                n += take
                if n >= budget:
                    break
            return n

        healthy = [bytearray() for _ in range(N_PEERS - 1)]

        def mk(buf):
            def sink(views):
                n = 0
                for v in views:
                    buf.extend(bytes(v))
                    n += len(v)
                return n
            return sink

        p_stall = srv.attach_peer("staller", sink=stall_sink)
        ps = [srv.attach_peer(f"h{i}", sink=mk(healthy[i]))
              for i in range(N_PEERS - 1)]
        t0 = time.monotonic()
        for off in range(0, len(WIRE), 2048):
            srv.publish(WIRE[off:off + 2048])
        srv.seal()
        for i, p in enumerate(ps):
            assert p.wait_done(10), f"healthy peer {i} hung"
        healthy_done = time.monotonic() - t0
        assert healthy_done < 1.5, \
            f"healthy peers waited on the staller: {healthy_done:.2f}s"
        for i, p in enumerate(ps):
            st = p.stats()
            assert bytes(healthy[i]) == WIRE
            assert st["lat_p99_ms"] is not None
            assert st["lat_p99_ms"] < P99_BUDGET_MS
        assert p_stall.wait_done(10)
        assert time.monotonic() - t0 >= 3.0  # it really did stall
        assert bytes(stalled) == WIRE  # window-bounded, never corrupted
    finally:
        srv.close()


def test_shed_peer_slot_is_released_for_a_replacement():
    """A shed peer releases its admission slot: a full fan-out admits
    a replacement after shedding (the bounded-state contract)."""
    srv = FanoutServer(max_peers=2, stall_timeout=0.1,
                       retention_budget=1 << 24)
    try:
        ok_buf = bytearray()

        def ok_sink(views):
            n = 0
            for v in views:
                ok_buf.extend(bytes(v))
                n += len(v)
            return n

        p_ok = srv.attach_peer("ok", sink=ok_sink)
        p_bad = srv.attach_peer("bad", sink=lambda vs: 0)
        srv.publish(WIRE[:8192])
        deadline = time.monotonic() + 5
        while p_bad.shed_reason is None and time.monotonic() < deadline:
            time.sleep(0.02)
        assert p_bad.shed_reason == "stall"
        p_bad.close()  # teardown releases the slot
        fresh_buf = bytearray()

        def fresh_sink(views):
            n = 0
            for v in views:
                fresh_buf.extend(bytes(v))
                n += len(v)
            return n

        p_fresh = srv.attach_peer("fresh", sink=fresh_sink, offset=0)
        srv.publish(WIRE[8192:16384])
        srv.seal()
        assert p_ok.wait_done(10) and p_fresh.wait_done(10)
        assert bytes(ok_buf) == WIRE[:16384]
        assert bytes(fresh_buf) == WIRE[:16384]
    finally:
        srv.close()
