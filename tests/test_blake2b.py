"""Device BLAKE2b vs the host reference implementation (hashlib).

SURVEY.md §7 step 3: "validate digests against a host reference
implementation". Covers empty input, sub-block, exact-block, multi-block,
variable lengths in one padded batch, and non-default digest sizes.
"""

import hashlib
import random

import pytest

from dat_replication_protocol_tpu.ops import blake2b as b2


def host(p: bytes, n: int = 32) -> bytes:
    return hashlib.blake2b(p, digest_size=n).digest()


@pytest.mark.parametrize(
    "payload",
    [
        b"",
        b"abc",
        b"a" * 127,
        b"b" * 128,
        b"c" * 129,
        b"d" * 256,
        bytes(range(256)) * 17,  # multi-block, non-uniform bytes
    ],
    ids=["empty", "abc", "127", "128", "129", "256", "4352"],
)
def test_single_payload_matches_hashlib(payload):
    assert b2.blake2b_batch([payload]) == [host(payload)]


def test_mixed_lengths_one_batch():
    rng = random.Random(7)
    payloads = [
        bytes(rng.getrandbits(8) for _ in range(rng.choice([0, 1, 63, 128, 200, 1000])))
        for _ in range(32)
    ]
    assert b2.blake2b_batch(payloads) == [host(p) for p in payloads]


def test_digest_sizes():
    for n in (16, 20, 32, 48, 64):
        assert b2.blake2b_batch([b"hello world"], digest_size=n) == [
            host(b"hello world", n)
        ]


def test_large_payload_multiblock():
    p = bytes(range(256)) * 4096  # 1 MiB
    assert b2.blake2b_batch([p]) == [host(p)]


def test_order_preserved_across_buckets():
    # items alternate between very different sizes -> different buckets,
    # output order must still match submit order
    payloads = [b"x" * (1 if i % 2 else 5000) for i in range(10)]
    assert b2.blake2b_batch(payloads) == [host(p) for p in payloads]


def test_packing_roundtrip_shapes():
    mh, ml, lengths = b2.pack_payloads([b"abc", b"y" * 130])
    assert mh.shape == (2, 2, 16) and ml.shape == (2, 2, 16)
    assert list(lengths) == [3, 130]
