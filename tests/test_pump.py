"""Unit layer for the kernel-bypass wire pump (ISSUE 14).

Syscall-batch edge cases the C loops must survive: partial sendmmsg
acceptance, EAGAIN mid-batch, fd death mid-loop, zero-length and
single-byte frames straddling receive batches, pipes (no mmsg support)
— plus the route selector and the fan-out gather's zero-Python-bytes
counter proof.  The byte-identical chaos sweep lives in
tests/test_pump_parity.py.
"""

from __future__ import annotations

import os
import socket
import threading
import time

import numpy as np
import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.obs import metrics as obs_metrics
from dat_replication_protocol_tpu.runtime import native
from dat_replication_protocol_tpu.session import pump
from dat_replication_protocol_tpu.session.decoder import Decoder
from dat_replication_protocol_tpu.wire.framing import TYPE_BLOB, frame

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable")


def _recv_all(sock: socket.socket) -> bytes:
    parts = []
    while True:
        d = sock.recv(1 << 16)
        if not d:
            return b"".join(parts)
        parts.append(d)


def _gather_for(payloads):
    g = pump.SpanGather()
    n = g.fill([memoryview(p) for p in payloads])
    return g, n


# -- probe / route selector ---------------------------------------------------


def test_probe_reports_syscall_tier():
    caps = pump.probe_caps()
    assert caps["native_available"] is True
    assert caps["route"] in ("native", "python")
    assert isinstance(caps["recvmmsg"], bool)
    assert isinstance(caps["sendmmsg"], bool)


def test_route_selector_resolution(monkeypatch):
    monkeypatch.setenv("DAT_PUMP", "python")
    assert pump.effective_pump_route() == "python"
    monkeypatch.setenv("DAT_PUMP", "native")
    assert pump.effective_pump_route() == "native"
    # unrecognized values resolve to the default (native when the
    # library loads — the DAT_CDC_ROUTE doctrine)
    monkeypatch.setenv("DAT_PUMP", "iouring")
    assert pump.effective_pump_route() == "native"
    monkeypatch.delenv("DAT_PUMP")
    assert pump.effective_pump_route() == "native"
    # no native library = no native route, whatever the env asks
    monkeypatch.setenv("DAT_NATIVE_DISABLE", "1")
    monkeypatch.setenv("DAT_PUMP", "native")
    assert pump.effective_pump_route() == "python"


# -- batched receive ----------------------------------------------------------


def test_recv_scan_batches_and_indexes(monkeypatch):
    monkeypatch.setenv("DAT_PUMP", "native")
    a, b = socket.socketpair()
    try:
        wire = frame(TYPE_BLOB, b"x" * 1000) * 40
        a.sendall(wire)
        a.shutdown(socket.SHUT_WR)
        dec = Decoder()
        got = []
        dec.blob(lambda blob, done: blob.collect(
            lambda data: (got.append(data), done())))
        pump.recv_pump(dec, b.fileno())
        assert dec.finished and len(got) == 40
        assert all(g == b"x" * 1000 for g in got)
    finally:
        a.close()
        b.close()


def test_zero_length_and_single_byte_frames_straddle_batches(monkeypatch):
    """A zero-length blob frame (flen=1: id only) and frames whose
    headers arrive ONE BYTE PER PUMP BATCH must decode exactly like a
    whole-buffer write — batch boundaries are not frame boundaries."""
    monkeypatch.setenv("DAT_PUMP", "native")
    wire = (frame(TYPE_BLOB, b"") + frame(TYPE_BLOB, b"z")
            + frame(TYPE_BLOB, b"") + frame(TYPE_BLOB, b"tail"))
    a, b = socket.socketpair()
    try:
        dec = Decoder()
        got = []
        dec.blob(lambda blob, done: blob.collect(
            lambda data: (got.append(data), done())))

        def feed():
            # one byte per send, paced so most land in separate pump
            # batches (the blocking first read takes whatever is there)
            for i in range(len(wire)):
                a.sendall(wire[i:i + 1])
                if i % 3 == 0:
                    time.sleep(0.002)
            a.shutdown(socket.SHUT_WR)

        t = threading.Thread(target=feed, daemon=True)
        t.start()
        pump.recv_pump(dec, b.fileno())
        t.join(10)
        assert dec.finished
        assert got == [b"", b"z", b"", b"tail"]
        assert dec.blobs == 4
    finally:
        a.close()
        b.close()


def test_recv_pump_on_pipe_degrades_to_plain_reads(monkeypatch):
    """Pipes have no recvmmsg (ENOTSOCK): the pump's wakeup read must
    carry the session alone — the sidecar --stdio shape."""
    monkeypatch.setenv("DAT_PUMP", "native")
    r, w = os.pipe()
    try:
        wire = frame(TYPE_BLOB, b"p" * 500) * 8
        os.write(w, wire)
        os.close(w)
        w = None
        dec = Decoder()
        got = []
        dec.blob(lambda blob, done: blob.collect(
            lambda data: (got.append(data), done())))
        pump.recv_pump(dec, r)
        assert dec.finished and len(got) == 8
    finally:
        os.close(r)
        if w is not None:
            os.close(w)


def test_write_indexed_falls_back_mid_frame():
    """The bulk entry only installs at a clean boundary; mid-frame it
    must route through write() with identical results."""
    wire = frame(TYPE_BLOB, b"A" * 1000)
    dec = Decoder()
    got = []
    dec.blob(lambda blob, done: blob.collect(
        lambda data: (got.append(data), done())))
    dec.write(wire[:100])  # now mid-blob
    starts = np.zeros(4, np.int64)
    lens = np.zeros(4, np.int64)
    ids = np.zeros(4, np.uint8)
    # a (bogus) index must be ignored: the parser is mid-frame
    ok = dec.write_indexed(wire[100:], starts, lens, ids, 1, 50)
    assert ok
    dec.end()
    assert got == [b"A" * 1000]


# -- gather send --------------------------------------------------------------


def test_send_spans_blocking_gather_exact_bytes():
    payloads = [os.urandom(137) for _ in range(300)]
    g, n = _gather_for(payloads)
    a, b = socket.socketpair()
    try:
        got = {}
        t = threading.Thread(target=lambda: got.__setitem__("d", _recv_all(b)),
                             daemon=True)
        t.start()
        w = native.pump_send_spans(a.fileno(), g.addrs, g.lens, n, g.stats)
        a.shutdown(socket.SHUT_WR)
        t.join(10)
        assert w == sum(len(p) for p in payloads)
        assert got["d"] == b"".join(payloads)
        # the whole 300-span batch cost far fewer kernel entries
        assert int(g.stats[0]) < 300
    finally:
        g.release()
        a.close()
        b.close()


def test_send_spans_nb_eagain_mid_batch_returns_accepted():
    """A non-blocking fd that stops accepting mid-batch must return the
    accepted byte count (no exception, no spin) — the fan-out window
    bookkeeping contract."""
    a, b = socket.socketpair()
    try:
        a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 16384)
        a.setblocking(False)
        payloads = [b"q" * 4096 for _ in range(200)]  # >> the send buffer
        g, n = _gather_for(payloads)
        accepted = pump.send_spans_nb(a.fileno(), g, n)
        g.release()
        assert 0 < accepted < sum(len(p) for p in payloads)
        # drain and finish: partial acceptance resumes exactly at the
        # accepted offset (receiver sees one contiguous stream)
        whole = b"".join(payloads)
        got = []
        sent = accepted
        b.setblocking(False)
        deadline = time.monotonic() + 30
        while (sent < len(whole) or len(b"".join(got)) < len(whole)) \
                and time.monotonic() < deadline:
            try:
                got.append(b.recv(1 << 16))
            except BlockingIOError:
                pass
            if sent < len(whole):
                g2, n2 = _gather_for([whole[sent:]])
                sent += pump.send_spans_nb(a.fileno(), g2, n2)
                g2.release()
        assert b"".join(got) == whole
    finally:
        a.close()
        b.close()


def test_send_to_dead_fd_raises_oserror():
    a, b = socket.socketpair()
    a_fd = os.dup(a.fileno())
    a.close()
    b.close()
    os.close(a_fd)  # fd is gone: the pump must surface EBADF, not hang
    g, n = _gather_for([b"x" * 100])
    with pytest.raises(OSError):
        pump.send_spans_nb(a_fd, g, n)
    g.release()


def test_send_pump_partial_writes_resume(monkeypatch):
    """Blocking gather against a slow reader: partial kernel accepts
    resume mid-span natively; every byte arrives in order."""
    monkeypatch.setenv("DAT_PUMP", "native")
    enc = protocol.encode()
    blob = enc.blob(2 << 20)
    blob.write(os.urandom(2 << 20))
    blob.end()
    enc.finalize()
    a, b = socket.socketpair()
    try:
        a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 32768)
        got = {}

        def slow_reader():
            parts = []
            while True:
                d = b.recv(8192)
                if not d:
                    break
                parts.append(d)
                time.sleep(0.0002)
            got["d"] = b"".join(parts)

        t = threading.Thread(target=slow_reader, daemon=True)
        t.start()
        pump.send_pump(enc, a.fileno(),
                       close=lambda: a.shutdown(socket.SHUT_WR))
        t.join(30)
        from dat_replication_protocol_tpu.wire.framing import frame_wire_len

        assert len(got["d"]) == frame_wire_len(2 << 20)
    finally:
        a.close()
        b.close()


# -- pump_reader / pump_writer drop-ins --------------------------------------


def test_pump_io_roundtrip(monkeypatch):
    monkeypatch.setenv("DAT_PUMP", "native")
    a, b = socket.socketpair()
    try:
        wr = pump.pump_writer(a.fileno())
        rd = pump.pump_reader(b.fileno())
        payload = os.urandom(300_000)
        t = threading.Thread(
            target=lambda: (wr(payload), a.shutdown(socket.SHUT_WR)),
            daemon=True)
        t.start()
        parts = []
        while True:
            d = rd(65536)
            if not d:
                break
            parts.append(d)
        t.join(10)
        assert b"".join(parts) == payload
    finally:
        a.close()
        b.close()


# -- fan-out gather: zero Python-owned payload bytes --------------------------


def test_fanout_native_gather_counter_proof(monkeypatch, obs_enabled):
    """On the native route every delivered broadcast byte rides the
    native gather (transport.pump.gather.bytes == fanout.sent.bytes):
    payload bytes go kernel-ward as (address, length) spans over
    BroadcastLog segment memory — no Python-owned copies on the hot
    path — while digest work stays zero however many peers attach
    (the hash-once economics are the source session's, untouched)."""
    from dat_replication_protocol_tpu.fanout import FanoutServer

    monkeypatch.setenv("DAT_PUMP", "native")
    srv = FanoutServer(max_peers=8, window_bytes=1 << 22)
    socks = []
    peers = []
    try:
        assert srv._gather is not None  # the route resolved native
        got = {}
        readers = []
        for i in range(4):
            a, b = socket.socketpair()
            socks.append((a, b))
            peers.append(srv.attach_peer(f"p{i}", fd=a.fileno(), offset=0))
            t = threading.Thread(
                target=lambda i=i, b=b: got.__setitem__(i, _recv_all(b)),
                daemon=True)
            t.start()
            readers.append(t)
        payload = os.urandom(1 << 20)
        srv.publish(payload)
        srv.seal()
        assert srv.drain(timeout=30)
        for i, (a, b) in enumerate(socks):
            peers[i].close()
            a.close()
        # the server's owned fd dups close with it; readers then see EOF
        srv.close()
        for t in readers:
            t.join(10)
        assert all(got.get(i) == payload for i in range(4))
        snap = obs_metrics.snapshot()["counters"]
        assert snap["fanout.sent.bytes"] == 4 * len(payload)
        assert snap["transport.pump.gather.bytes"] == 4 * len(payload)
        assert snap["device.native.hash.bytes"] == 0  # hash-once: zero here
    finally:
        srv.close()
        for a, b in socks:
            a.close()
            b.close()


def test_fanout_python_route_unchanged(monkeypatch):
    """DAT_PUMP=python pins the os.writev path (the server resolves at
    construction): same bytes, gather counter dark."""
    from dat_replication_protocol_tpu.fanout import FanoutServer

    monkeypatch.setenv("DAT_PUMP", "python")
    srv = FanoutServer(max_peers=4)
    a, b = socket.socketpair()
    try:
        assert srv._gather is None
        peer = srv.attach_peer("p0", fd=a.fileno(), offset=0)
        payload = os.urandom(100_000)  # fits the kernel buffer whole
        srv.publish(payload)
        srv.seal()
        assert srv.drain(timeout=30)
        peer.close()
        a.close()
        srv.close()  # releases the owned fd dup -> reader sees EOF
        assert _recv_all(b) == payload
    finally:
        srv.close()
        a.close()
        b.close()


# -- sidecar route surfacing --------------------------------------------------


def test_stats_snapshot_carries_pump_route(monkeypatch):
    from dat_replication_protocol_tpu import sidecar

    monkeypatch.setenv("DAT_PUMP", "native")
    snap = sidecar.snapshot_stats()
    assert snap["pump"]["route"] == "native"
    assert snap["pump"]["native_available"] is True
    monkeypatch.setenv("DAT_PUMP", "python")
    assert sidecar.snapshot_stats()["pump"]["route"] == "python"


def test_hub_snapshot_carries_pump_route(monkeypatch):
    from dat_replication_protocol_tpu.hub import ReplicationHub

    monkeypatch.setenv("DAT_PUMP", "python")
    hub = ReplicationHub(max_sessions=2)
    try:
        assert hub.snapshot()["pump_route"] == "python"
    finally:
        hub.close()
