"""Device-offloaded session backend: DigestPipeline + streaming hashing.

Covers the streaming large-blob path added after round 1: blobs past
``stream_threshold`` hash incrementally in O(segment) memory (no host
join, no < 2 GiB cap) while digests still arrive in submit order and
before finalize.
"""

import hashlib
import random

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.backend.tpu_backend import (
    DigestPipeline,
    TpuDecoder,
    TpuEncoder,
    _HostStream,
)
from dat_replication_protocol_tpu.ops.blake2b import Blake2bStream


def _h(data: bytes) -> bytes:
    return hashlib.blake2b(data, digest_size=32).digest()


# ---------------------------------------------------------------------------
# DigestPipeline mixed-entry ordering
# ---------------------------------------------------------------------------


def test_pipeline_orders_streams_between_payloads():
    pl = DigestPipeline(max_batch=100)
    got = []
    pl.submit(b"aa", lambda d: got.append(("p0", d)))
    s = Blake2bStream(segment_bytes=128).update(b"s" * 300)
    pl.submit_stream(s, lambda d: got.append(("s1", d)))
    pl.submit(b"bb", lambda d: got.append(("p2", d)))
    pl.flush()
    assert [g[0] for g in got] == ["p0", "s1", "p2"]
    assert got[0][1] == _h(b"aa")
    assert got[1][1] == _h(b"s" * 300)
    assert got[2][1] == _h(b"bb")
    assert pl.hashed_bytes == 2 + 300 + 2


def test_pipeline_stream_only_flush():
    pl = DigestPipeline()
    got = []
    pl.submit_stream(_HostStream().update(b"xyz"), got.append)
    pl.flush()
    assert got == [_h(b"xyz")]


def test_pipeline_byte_cap_autodispatches():
    pl = DigestPipeline(max_batch=1000, max_batch_bytes=100)
    got = []
    pl.submit(b"z" * 60, got.append)
    assert pl.dispatches == 0
    pl.submit(b"z" * 60, got.append)
    assert pl.dispatches == 1  # device work started, delivery deferred
    pl.flush()
    assert len(got) == 2


def test_pipeline_item_cap_counts_streams():
    pl = DigestPipeline(max_batch=2)
    got = []
    pl.submit_stream(_HostStream().update(b"1"), got.append)
    assert pl.dispatches == 0
    pl.submit_stream(_HostStream().update(b"2"), got.append)
    assert pl.dispatches == 1
    pl.flush()
    assert got == [_h(b"1"), _h(b"2")]


def test_pipeline_async_overlap_and_bounded_inflight():
    # fake async engine: records when batches are dispatched vs collected,
    # proving submit/dispatch never blocks on results and that at most
    # max_inflight batches ride uncollected
    events = []

    def begin(payloads):
        events.append(("dispatch", len(payloads)))

        def collect():
            events.append(("collect", len(payloads)))
            return [_h(p) for p in payloads]

        return collect

    pl = DigestPipeline(hash_begin=begin, max_batch=2, max_inflight=2)
    got = []
    for i in range(8):
        pl.submit(b"%d" % i, got.append)
    # 4 batches dispatched; only 4 - max_inflight collected so far
    assert events.count(("dispatch", 2)) == 4
    assert events.count(("collect", 2)) == 2
    assert got == [_h(b"%d" % i) for i in range(4)]  # oldest-first, in order
    assert pl.inflight == 2
    pl.flush()
    assert events.count(("collect", 2)) == 4
    assert got == [_h(b"%d" % i) for i in range(8)]


def test_pipeline_flush_preserves_order_across_batches():
    pl = DigestPipeline(max_batch=2, max_inflight=10)
    got = []
    payloads = [b"a", b"bb", b"ccc", b"dddd", b"e"]
    for p in payloads:
        pl.submit(p, got.append)
    pl.flush()
    assert got == [_h(p) for p in payloads]


# ---------------------------------------------------------------------------
# streaming blob digests through the session ends
# ---------------------------------------------------------------------------


def _run_session(enc, dec, blob: bytes, chunk: int):
    digests = []
    dec.on_digest(lambda kind, seq, d: digests.append((kind, seq, d)))
    final = []
    dec.finalize(lambda done: (final.append(len(digests)), done()))
    ws = enc.blob(len(blob))
    p = protocol.pipe(enc, dec)
    for i in range(0, len(blob), chunk):
        ws.write(blob[i : i + chunk])
        p.pump()
    ws.end()
    enc.change({"key": "k", "change": 1, "from_": 0, "to": 1})
    enc.finalize()
    p.pump()
    assert p.done
    return digests, final


@pytest.mark.parametrize("threshold", [1, 1 << 30])
def test_decoder_blob_digest_streamed_vs_batched(threshold):
    blob = random.Random(1).randbytes(5000)
    enc = protocol.encode()
    dec = TpuDecoder(stream_threshold=threshold)
    digests, final = _run_session(enc, dec, blob, chunk=777)
    assert ("blob", 0, _h(blob)) in digests
    # flush-before-finalize: all digests delivered before the hook ran
    assert final == [len(digests)]
    if threshold == 1:
        assert not dec._blob_parts  # nothing joined in host RAM


def test_decoder_streaming_bounded_memory():
    # blob larger than max_batch_bytes flows through without ever being
    # materialized: neither parts nor pipeline payload bytes hold it
    blob = random.Random(2).randbytes(300_000)
    pl = DigestPipeline(max_batch_bytes=10_000)
    dec = TpuDecoder(pipeline=pl, stream_threshold=100_000)
    enc = protocol.encode()
    digests, _ = _run_session(enc, dec, blob, chunk=9999)
    assert ("blob", 0, _h(blob)) in digests
    assert pl.hashed_bytes >= len(blob)
    assert not dec._blob_parts and not dec._blob_streams


@pytest.mark.parametrize("threshold", [1, 1 << 30])
def test_encoder_blob_digest_streamed_vs_batched(threshold):
    blob = random.Random(3).randbytes(4096)
    enc = TpuEncoder(stream_threshold=threshold)
    digests = []
    enc.on_digest(lambda kind, seq, d: digests.append((kind, seq, d)))
    dec = protocol.decode()
    ws = enc.blob(len(blob))
    ws.write(blob[:1000])
    ws.end(blob[1000:])
    enc.finalize()
    protocol.pipe(enc, dec)
    assert ("blob", 0, _h(blob)) in digests


def test_encoder_streaming_change_and_blob_order():
    enc = TpuEncoder(stream_threshold=10)
    got = []
    enc.on_digest(lambda kind, seq, d: got.append((kind, seq)))
    enc.change({"key": "a", "change": 1, "from_": 0, "to": 1})
    ws = enc.blob(64)
    ws.write(b"x" * 64)
    ws.end()
    enc.change({"key": "b", "change": 2, "from_": 1, "to": 2})
    enc.finalize()
    protocol.pipe(enc, protocol.decode())
    assert got == [("change", 0), ("blob", 0), ("change", 1)]


def test_host_stream_matches_hashlib():
    s = _HostStream()
    s.update(b"abc").update(memoryview(b"def"))
    assert s.digest() == _h(b"abcdef")
    assert s.length == 6


def test_hash_engine_routing_follows_backend(monkeypatch):
    """Round-3 verdict weak #4: on a CPU-only jax the batch engine must be
    hashlib (0.33 GiB/s) not the XLA scan (0.031 GiB/s) — device batching
    only when a device exists ("batch or stay home")."""
    import jax

    from dat_replication_protocol_tpu.backend import tpu_backend as tb

    assert jax.default_backend() == "cpu"  # test env forces cpu
    monkeypatch.delenv("DAT_DEVICE_HASH", raising=False)
    assert tb._device_hash_begin_factory() is None  # -> _host_hash_batch
    monkeypatch.setenv("DAT_DEVICE_HASH", "1")
    assert tb._device_hash_begin_factory() is not None  # forced device path
    monkeypatch.setenv("DAT_DEVICE_HASH", "0")
    assert tb._device_hash_begin_factory() is None


def test_prefer_host_override_combinations(monkeypatch):
    """prefer_host: env override wins, then the configured platform
    string, and the decision never initializes a device backend."""
    from dat_replication_protocol_tpu.utils.routing import prefer_host

    monkeypatch.setenv("X_ROUTE", "0")
    assert prefer_host("X_ROUTE") is True  # forced host
    monkeypatch.setenv("X_ROUTE", "1")
    assert prefer_host("X_ROUTE") is False  # forced device
    monkeypatch.delenv("X_ROUTE", raising=False)
    # test env configures the cpu platform (conftest): host wins
    assert prefer_host("X_ROUTE") is True


@pytest.mark.parametrize("dispatch", ["c", "python"])
def test_bulk_sink_digests_match_streaming_path(dispatch, monkeypatch):
    """backend='tpu' decoding must produce the identical digest sequence
    (kind, seq, digest) whether frames arrive in one bulk write (the
    C/Python fast loop's payload sink) or byte-dribbled through the
    streaming scanner — and interleaved blobs must keep their relative
    order.  Runs against BOTH fast-loop implementations."""
    import os

    if dispatch == "python":
        monkeypatch.setenv("DAT_FASTPATH_DISABLE", "1")

    import dat_replication_protocol_tpu as protocol
    from dat_replication_protocol_tpu.wire.change_codec import encode_change
    from dat_replication_protocol_tpu.wire.framing import (
        TYPE_BLOB,
        TYPE_CHANGE,
        frame,
    )

    os.environ.setdefault("DAT_DEVICE_HASH", "0")
    parts = []
    for i in range(300):
        parts.append(frame(TYPE_CHANGE, encode_change({
            "key": f"k{i}", "change": i, "from": i, "to": i + 1,
            "value": bytes([i & 255]) * (i % 40)})))
        if i % 13 == 0:
            parts.append(frame(TYPE_BLOB, bytes([i & 255]) * (i % 500 + 1)))
    wire = b"".join(parts)

    def drive(chunk):
        dec = protocol.decode(backend="tpu")
        got = []
        dec.on_digest(lambda k, s, d: got.append((k, s, d)))
        dec.change(lambda ch, done: done())
        dec.blob(lambda b, done: b.collect(lambda _d: done()))
        for off in range(0, len(wire), chunk):
            dec.write(wire[off:off + chunk])
        dec.end()
        assert dec.finished
        return got

    bulk = drive(len(wire))
    tiny = drive(7)
    assert bulk == tiny
    assert len(bulk) == 300 + sum(1 for i in range(300) if i % 13 == 0)
    # per-kind seqs are each contiguous from 0
    for kind in ("change", "blob"):
        seqs = [s for k, s, _ in bulk if k == kind]
        assert seqs == list(range(len(seqs)))


def test_digestless_tpu_decoder_never_hashes_on_bulk():
    """No on_digest registered -> the bulk sink must not collect or hash
    anything (the streaming path's digest_cbs guard, bulk edition)."""
    import dat_replication_protocol_tpu as protocol
    from dat_replication_protocol_tpu.wire.change_codec import encode_change
    from dat_replication_protocol_tpu.wire.framing import TYPE_CHANGE, frame

    wire = b"".join(frame(TYPE_CHANGE, encode_change({
        "key": f"k{i}", "change": i, "from": i, "to": i + 1}))
        for i in range(500))
    dec = protocol.decode(backend="tpu")
    seen = []
    dec.change(lambda ch, done: (seen.append(ch.key), done()))
    dec.write(wire)
    dec.end()
    assert dec.finished and len(seen) == 500
    assert dec.digest_pipeline.hashed_bytes == 0
    assert dec.digest_pipeline.dispatches == 0
    # seq accounting still advanced (a late-registered digest consumer
    # keeps correct sequence numbers)
    assert dec._change_seq == 500


def test_tpu_decoder_subclass_override_fires_on_bulk_writes():
    """The sink opt-in must NOT inherit: a subclass overriding
    _deliver_change gets its override on bulk writes too (round-5
    review: an inherited flag silently bypassed overrides only for
    large writes)."""
    import dat_replication_protocol_tpu as protocol  # noqa: F401
    from dat_replication_protocol_tpu.backend.tpu_backend import TpuDecoder
    from dat_replication_protocol_tpu.wire.change_codec import encode_change
    from dat_replication_protocol_tpu.wire.framing import TYPE_CHANGE, frame

    hooked = []

    class MyDecoder(TpuDecoder):
        def _deliver_change(self, change, payload):
            hooked.append(bytes(payload))
            super()._deliver_change(change, payload)

    wire = b"".join(frame(TYPE_CHANGE, encode_change({
        "key": f"k{i}", "change": i, "from": i, "to": i + 1}))
        for i in range(300))
    dec = MyDecoder()
    seen = []
    dec.change(lambda ch, done: (seen.append(ch.key), done()))
    dec.write(wire)  # one big write: would ride the fast loop if the
    dec.end()        # flag inherited
    assert dec.finished
    assert len(seen) == 300
    assert len(hooked) == 300, "override bypassed on the bulk path"
