"""Rateless coded-symbol reconciliation (ISSUE 10): property layer.

The decode contract under fuzz: across seeds and diff shapes
(insertions, deletions, value flips; k = 0, 1, 17, 1000), peeling
recovers EXACTLY the symmetric difference — never a wrong element,
never a missed one — and the engines (numpy reference, native C,
jitted JAX scatter-add) build byte-identical symbol prefixes.
"""

from __future__ import annotations

import hashlib

import numpy as np
import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.ops import rateless as rl
from dat_replication_protocol_tpu.runtime import native
from dat_replication_protocol_tpu.wire import reconcile_codec as rc
from dat_replication_protocol_tpu.wire.framing import (
    CAP_RECONCILE,
    ProtocolError,
)


def _digests(items) -> np.ndarray:
    if not items:
        return np.empty((0, 32), np.uint8)
    return np.frombuffer(
        b"".join(hashlib.blake2b(x, digest_size=32).digest() for x in items),
        np.uint8,
    ).reshape(-1, 32).copy()


def _stream_decode(da: np.ndarray, db: np.ndarray, batch0: int = 16):
    """A's symbols streamed to a decoder over B's set; returns
    (digests, signs, symbols_sent)."""
    syms = rl.CodedSymbols(rl.dedupe_digests(da)[0])
    dec = rl.PeelDecoder(db)
    m, sent = batch0, 0
    while True:
        dec.add_symbols(sent, syms.extend(m)[sent:])
        sent = m
        out = dec.try_decode()
        if out is not None:
            return out[0], out[1], sent
        m *= 2
        assert m <= 1 << 20, "decode never completed"


def _diff_sets(da, db):
    a = {bytes(d) for d in da}
    b = {bytes(d) for d in db}
    return a - b, b - a


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.parametrize("k", [0, 1, 17])
def test_peeling_recovers_exact_symmetric_difference(seed, k):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(50, 900))
    base = [b"rec-%06d" % i for i in range(n)]
    a_items = list(base)
    b_items = list(base)
    # spread k mutations across all three shapes
    for i in range(k):
        which = (seed + i) % 3
        if which == 0 and b_items:  # deletion from b
            b_items.pop(int(rng.integers(0, len(b_items))))
        elif which == 1:  # insertion into b
            b_items.insert(int(rng.integers(0, len(b_items) + 1)),
                           b"new-%d-%d" % (seed, i))
        else:  # value flip
            at = int(rng.integers(0, len(b_items)))
            b_items[at] = b_items[at] + b"~v2"
    da, db = _digests(a_items), _digests(b_items)
    got_d, got_s, sent = _stream_decode(da, db)
    only_a, only_b = _diff_sets(da, db)
    assert {bytes(d) for d, s in zip(got_d, got_s) if s == 1} == only_a
    assert {bytes(d) for d, s in zip(got_d, got_s) if s == -1} == only_b
    diff = len(only_a) + len(only_b)
    if diff:
        # rateless economy: the stream never runs past ~2x the decode
        # point, and the decode point is a small multiple of the diff
        assert sent <= max(64, 8 * diff)


def test_k1000_diff_decodes_with_linear_symbols():
    rng = np.random.default_rng(7)
    n, k = 3000, 1000
    base = rng.integers(0, 256, (n + k, 32), dtype=np.uint8)
    da = base[:n].copy()  # drops the k tail
    db = np.concatenate([base[k:n], base[n:]])  # drops head k, adds tail k
    got_d, got_s, sent = _stream_decode(da, db, batch0=256)
    only_a, only_b = _diff_sets(da, db)
    assert {bytes(d) for d, s in zip(got_d, got_s) if s == 1} == only_a
    assert {bytes(d) for d, s in zip(got_d, got_s) if s == -1} == only_b
    assert len(got_d) == 2 * k
    # wire economy at scale: <= ~2.2 symbols per differing element once
    # the doubling schedule's overshoot is accounted
    assert sent <= 2.5 * 2 * k


def test_identical_sets_decode_empty_immediately():
    da = _digests([b"x%d" % i for i in range(400)])
    syms = rl.CodedSymbols(da)
    dec = rl.PeelDecoder(da.copy())
    dec.add_symbols(0, syms.extend(8))
    out = dec.try_decode()
    assert out is not None and len(out[0]) == 0


def test_empty_vs_populated_bootstrap():
    db = _digests([b"b%d" % i for i in range(120)])
    got_d, got_s, _ = _stream_decode(_digests([]), db)
    assert (got_s == -1).all() and len(got_d) == 120


def test_duplicate_records_collapse_to_set_semantics():
    # a duplicated record must not brick the decode (count-2 cells
    # never peel): dedupe is part of the element contract
    items = [b"dup"] * 5 + [b"u%d" % i for i in range(50)]
    da = _digests(items)
    uniq, first = rl.dedupe_digests(da)
    assert len(uniq) == 51 and first[0] == 0
    db = _digests([b"u%d" % i for i in range(50)])
    got_d, got_s, _ = _stream_decode(da, db)
    assert len(got_d) == 1 and got_s[0] == 1
    assert bytes(got_d[0]) == hashlib.blake2b(
        b"dup", digest_size=32).digest()


def test_dedupe_resolves_first_word_collisions_exactly():
    # two DISTINCT digests sharing their first 8 bytes must both
    # survive dedupe (the u64 fast path may not silently merge them)
    a = np.arange(32, dtype=np.uint8).reshape(1, 32)
    b = a.copy()
    b[0, 31] ^= 0xFF
    d = np.concatenate([a, b, a])  # one true duplicate of a
    uniq, first = rl.dedupe_digests(d)
    assert len(uniq) == 2 and first.tolist() == [0, 1]


# -- engine parity -----------------------------------------------------------


def _parity_digests(n=257, seed=3):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (n, 32), dtype=np.uint8)


def test_jax_build_matches_numpy_reference_byte_for_byte():
    d = _parity_digests()
    for schedule in [(64,), (16, 64, 192)]:
        out = {}
        for eng in ("numpy", "device"):
            cs = rl.CodedSymbols(d, engine=eng)
            for m in schedule:
                cells = cs.extend(m)
            out[eng] = np.asarray(cells)
        assert out["numpy"].tobytes() == out["device"].tobytes(), schedule


def test_native_build_matches_numpy_reference_byte_for_byte():
    if not native.available():
        pytest.skip("native library unavailable")
    d = _parity_digests(seed=4)
    for schedule in [(64,), (16, 64, 192)]:
        out = {}
        for eng in ("numpy", "host"):
            cs = rl.CodedSymbols(d, engine=eng)
            for m in schedule:
                cells = cs.extend(m)
            out[eng] = np.asarray(cells)
        assert out["numpy"].tobytes() == out["host"].tobytes(), schedule


def test_index_cursor_is_incremental_and_deterministic():
    d = _parity_digests(64, seed=9)
    c1 = rl.IndexCursor(d)
    e1, i1 = c1.advance(256)
    c2 = rl.IndexCursor(d)
    parts = [c2.advance(16), c2.advance(64), c2.advance(256)]
    e2 = np.concatenate([p[0] for p in parts])
    i2 = np.concatenate([p[1] for p in parts])
    # same multiset of participations regardless of schedule
    a = sorted(zip(e1.tolist(), i1.tolist()))
    b = sorted(zip(e2.tolist(), i2.tolist()))
    assert a == b
    # every element participates at index 0 (the paper's construction)
    assert set(e1[i1 == 0].tolist()) == set(range(64))


# -- payload codec -----------------------------------------------------------


def test_codec_roundtrips():
    cells = np.arange(33, dtype=np.uint32).reshape(3, 11)
    digs = np.arange(64, dtype=np.uint8).reshape(2, 32)
    for payload, checks in [
        (rc.encode_begin(12), dict(kind=rc.RC_BEGIN, n=12)),
        (rc.encode_symbols(7, cells), dict(kind=rc.RC_SYMBOLS, start=7)),
        (rc.encode_done(9, digs), dict(kind=rc.RC_DONE, n=9)),
        (rc.encode_more(5), dict(kind=rc.RC_MORE, n=5)),
        (rc.encode_fail(3, "why"), dict(kind=rc.RC_FAIL, n=3,
                                        reason="why")),
    ]:
        msg = rc.decode_reconcile(payload)
        for k, v in checks.items():
            assert getattr(msg, k) == v, (k, payload)
    msg = rc.decode_reconcile(rc.encode_symbols(7, cells))
    assert np.array_equal(msg.cells, cells)
    msg = rc.decode_reconcile(rc.encode_done(9, digs))
    assert np.array_equal(msg.digests, digs)


@pytest.mark.parametrize("payload", [
    b"",                                   # empty
    bytes([9]),                            # unknown subtype
    bytes([rc.RC_BEGIN, 99, 1]),           # bad version
    rc.encode_begin(3) + b"x",             # trailing bytes
    rc.encode_symbols(0, np.zeros((2, 11), np.uint32))[:-3],  # torn cells
    rc.encode_done(1, np.zeros((2, 32), np.uint8))[:-1],      # torn digest
    rc.encode_more(1) + b"\x00",           # trailing bytes
])
def test_codec_rejects_structural_corruption(payload):
    with pytest.raises(ValueError):
        rc.decode_reconcile(payload)


# -- session-layer integration ----------------------------------------------


def test_unnegotiated_encoder_refuses_reconcile_frames_and_stays_golden():
    # the golden contract: an encoder that was never told CAP_RECONCILE
    # cannot emit a reconcile frame at all, so its wire is the
    # reference wire byte-exactly (same doctrine as ChangeBatch)
    e = protocol.encode()
    with pytest.raises(ValueError, match="CAP_RECONCILE"):
        e.reconcile_frame(rc.encode_begin(1))
    e.change({"key": "a", "change": 1, "from": 0, "to": 1})
    e.finalize()
    wire = e.read()
    ref = protocol.encode()
    ref.change({"key": "a", "change": 1, "from": 0, "to": 1})
    ref.finalize()
    assert wire == ref.read()  # byte-exact: the refusal left no residue


def test_decoder_advertises_cap_reconcile():
    assert protocol.Decoder.capabilities() & CAP_RECONCILE


def test_reconcile_frames_count_in_frame_accounting():
    e = protocol.encode(peer_caps=CAP_RECONCILE)
    d = protocol.decode()
    seen = []
    d.reconcile(lambda m, done: (seen.append(m), done()))
    e.change({"key": "x", "change": 1, "from": 0, "to": 1})
    e.reconcile_frame(rc.encode_more(1))
    e.change({"key": "y", "change": 2, "from": 0, "to": 1})
    e.finalize()
    wire = e.read()
    for off in range(0, len(wire), 5):
        d.write(wire[off:off + 5])
    d.end()
    assert d.finished and len(seen) == 1
    assert d.reconcile_frames == 1
    assert d._frames_delivered() == 3
    ckpt = d.checkpoint()
    assert ckpt.frame == 3 and ckpt.wire_offset == len(wire)


def test_unhandled_reconcile_frames_drop_without_deadlock():
    e = protocol.encode(peer_caps=CAP_RECONCILE)
    d = protocol.decode()  # no reconcile handler registered
    e.reconcile_frame(rc.encode_begin(4))
    e.change({"key": "x", "change": 1, "from": 0, "to": 1})
    e.finalize()
    d.write(e.read())
    d.end()
    assert d.finished and d.changes == 1 and d.reconcile_frames == 1


def test_corrupt_reconcile_payload_is_structured_protocol_error():
    from dat_replication_protocol_tpu.wire.framing import (
        TYPE_RECONCILE,
        frame,
    )

    d = protocol.decode()
    errs = []
    d.on_error(errs.append)
    d.write(frame(TYPE_RECONCILE, bytes([250, 1])))
    assert d.destroyed
    assert isinstance(errs[0], ProtocolError)
    assert errs[0].offset is not None and errs[0].frame == 0


# -- driver-level convergence ------------------------------------------------


def _mk_records(keys, flip=()):
    return [{"key": k, "change": i, "from": i, "to": i + 1,
             "value": (b"V2:" if k in flip else b"v:") + k.encode()}
            for i, k in enumerate(keys)]


def test_reconcile_local_converges_and_meters_wire():
    from dat_replication_protocol_tpu.runtime.reconcile_driver import (
        RatelessReplica,
        reconcile_local,
    )

    keys = [f"key-{i:05d}" for i in range(800)]
    flip = {"key-00013", "key-00777"}
    a = RatelessReplica(_mk_records(keys + ["only-a-%d" % i
                                            for i in range(3)]))
    b = RatelessReplica(_mk_records(keys + ["only-b-%d" % i
                                            for i in range(5)], flip=flip))
    out = reconcile_local(a, b)
    assert len(out["a_rows"]) == 3 + 2  # a-only + a's flipped versions
    assert len(out["b_rows"]) == 5 + 2
    # convergence: both sides end holding the identical record set
    sa = {str(a.cols.row(i)) for i in range(len(a.cols))}
    sb = {str(b.cols.row(i)) for i in range(len(b.cols))}
    sa |= {str(out["b_cols"].row(i)) for i in range(len(out["b_cols"]))}
    sb |= {str(out["a_cols"].row(i)) for i in range(len(out["a_cols"]))}
    assert sa == sb
    # O(diff) wire: a few KiB against a log of 800 records
    assert out["wire_bytes"] < 64 * len(a.cols)
    assert out["wire_bytes"] == out["wire_a2b"] + out["wire_b2a"]


def test_live_duplex_drivers_converge_over_socketpair():
    import socket
    import threading

    from dat_replication_protocol_tpu.runtime.reconcile_driver import (
        RatelessReplica,
        run_initiator,
        run_responder,
    )

    keys = [f"k-{i:04d}" for i in range(300)]
    a = RatelessReplica(_mk_records(keys + ["a-extra"]))
    b = RatelessReplica(_mk_records(keys + ["b-extra-1", "b-extra-2"]))
    s1, s2 = socket.socketpair()
    box = {}

    def responder():
        box["r"] = run_responder(
            b, s2.recv, s2.sendall,
            close_write=lambda: s2.shutdown(socket.SHUT_WR))

    t = threading.Thread(target=responder, daemon=True)
    t.start()
    ri = run_initiator(a, s1.recv, s1.sendall,
                       close_write=lambda: s1.shutdown(socket.SHUT_WR))
    t.join(20)
    assert not t.is_alive(), "responder hung"
    rr = box["r"]
    assert ri["ok"] and rr["ok"]
    assert ri["records_sent"] == 1 and rr["records_sent"] == 2
    assert {c.key for c in ri["received"]} == {"b-extra-1", "b-extra-2"}
    assert {c.key for c in rr["received"]} == {"a-extra"}
    s1.close()
    s2.close()


def test_responder_state_fails_structured_on_symbol_exhaustion():
    from dat_replication_protocol_tpu.runtime.reconcile_driver import (
        RatelessReplica,
        ResponderState,
    )

    b = RatelessReplica(_mk_records([f"k{i}" for i in range(40)]))
    state = ResponderState(b, overhead_cap=0.01)
    assert state.handle(rc.decode_reconcile(rc.encode_begin(40))) == []
    # garbage symbols that can never peel: cap trips -> FAIL reply +
    # ONE structured error from result()
    junk = np.arange(400 * 11, dtype=np.uint32).reshape(400, 11)
    replies = state.handle(
        rc.decode_reconcile(rc.encode_symbols(0, junk)))
    assert len(replies) == 1
    assert rc.decode_reconcile(replies[0]).kind == rc.RC_FAIL
    with pytest.raises(ProtocolError) as ei:
        state.result()
    assert ei.value.offset is not None


def test_responder_symbol_budget_is_independent_of_claimed_n():
    """A byzantine initiator claiming an astronomically large set must
    not move the responder's resource bound: the absolute max_symbols
    budget WINS over the claim-scaled overhead cap, and the session
    fails structured instead of growing without limit (the hub/fanout
    overload doctrine, restated for anti-entropy)."""
    from dat_replication_protocol_tpu.runtime.reconcile_driver import (
        RatelessReplica,
        ResponderState,
    )

    b = RatelessReplica(_mk_records([f"k{i}" for i in range(20)]))
    state = ResponderState(b, max_symbols=500)
    state.handle(rc.decode_reconcile(rc.encode_begin(1 << 50)))
    junk = np.arange(256 * 11, dtype=np.uint32).reshape(256, 11)
    replies = state.handle(
        rc.decode_reconcile(rc.encode_symbols(0, junk)))
    assert rc.decode_reconcile(replies[0]).kind == rc.RC_MORE
    replies = state.handle(
        rc.decode_reconcile(rc.encode_symbols(256, junk)))
    assert rc.decode_reconcile(replies[0]).kind == rc.RC_FAIL
    with pytest.raises(ProtocolError):
        state.result()


def test_responder_state_rejects_symbols_before_begin():
    from dat_replication_protocol_tpu.runtime.reconcile_driver import (
        RatelessReplica,
        ResponderState,
    )

    state = ResponderState(RatelessReplica(_mk_records(["a"])))
    replies = state.handle(rc.decode_reconcile(
        rc.encode_symbols(0, np.zeros((1, 11), np.uint32))))
    assert rc.decode_reconcile(replies[0]).kind == rc.RC_FAIL
    with pytest.raises(ProtocolError):
        state.result()


# -- weighted (variable-size element) extension (ISSUE 12) -------------------
#
# The snapshot bootstrap reconciles CDC chunk SETS: elements carry a
# byte length, the cell grows a length word, and participation density
# scales with the weight class.  Same contract as above: exact
# symmetric difference, byte-identical engines, deterministic cursor.


def _wparity_inputs(n: int = 257, seed: int = 2):
    rng = np.random.default_rng(seed)
    d = rng.integers(0, 256, (n, 32), dtype=np.uint8)
    # lengths spanning every weight class: 0 bytes up to ~16 MiB
    lens = (rng.integers(0, 1 << 24, n)
            * rng.integers(0, 2, n)).astype(np.int64)
    return d, lens


def test_weight_classes_match_the_definition():
    lens = np.array([0, 1, 4096, 8191, 8192, 1 << 20, 1 << 30], np.int64)
    got = rl.weight_classes(lens).tolist()
    want = [min(rl.RATELESS_W_CAP, int(ln) >> rl.RATELESS_W_SHIFT and
                (int(ln) >> rl.RATELESS_W_SHIFT).bit_length())
            for ln in lens]
    assert got == want
    # heavy chunks participate more densely than light ones
    heavy = rl.WeightedIndexCursor(
        _wparity_inputs(1)[0][:1], np.array([1 << 23]))
    light = rl.WeightedIndexCursor(
        _wparity_inputs(1)[0][:1], np.array([16]))
    assert len(heavy.advance(4096)[0]) > len(light.advance(4096)[0])


@pytest.mark.parametrize("seed,k", [(0, 0), (1, 1), (2, 17), (3, 100)])
def test_weighted_peeling_recovers_diff_with_lengths(seed, k):
    rng = np.random.default_rng(seed + 40)
    n = 400
    d = rng.integers(0, 256, (n + k, 32), dtype=np.uint8)
    lens = rng.integers(0, 1 << 22, n + k).astype(np.int64)
    # A = rows [0, n), B = rows [k, n+k): k only-in-A, k only-in-B,
    # n-k shared (identical lengths on shared rows)
    da, la = d[:n], lens[:n]
    db, lb = d[k:], lens[k:]
    syms = rl.WeightedSymbols(da, la)
    dec = rl.WeightedPeelDecoder(db, lb)
    m, sent = 16, 0
    while True:
        dec.add_symbols(sent, syms.extend(m)[sent:])
        sent = m
        out = dec.try_decode()
        if out is not None:
            break
        m *= 2
        assert m <= 1 << 20, "decode never completed"
    digests, rec_lens, signs = out
    assert len(digests) == 2 * k
    want = {bytes(d[i]): int(lens[i]) for i in range(k)}
    want.update({bytes(d[n + i]): int(lens[n + i]) for i in range(k)})
    got = {bytes(digests[i]): int(rec_lens[i]) for i in range(len(digests))}
    assert got == want  # every element's LENGTH recovered exactly
    # sign +1 = remote(A)-only, -1 = local(B)-only
    a_only = {bytes(digests[i]) for i in range(len(digests))
              if signs[i] == 1}
    assert a_only == {bytes(d[i]) for i in range(k)}


def test_weighted_identical_sets_decode_empty():
    d, lens = _wparity_inputs(64, seed=7)
    syms = rl.WeightedSymbols(d, lens)
    dec = rl.WeightedPeelDecoder(d, lens)
    dec.add_symbols(0, syms.extend(16))
    out = dec.try_decode()
    assert out is not None and len(out[0]) == 0


def test_weighted_engines_byte_identical():
    d, lens = _wparity_inputs()
    for schedule in [(64,), (16, 64, 192)]:
        out = {}
        for eng in ("numpy", "device") + (
                ("host",) if native.available() else ()):
            cs = rl.WeightedSymbols(d, lens, engine=eng)
            for m in schedule:
                cells = cs.extend(m)
            out[eng] = np.asarray(cells).tobytes()
        assert out["numpy"] == out["device"], schedule
        if "host" in out:
            assert out["numpy"] == out["host"], schedule


def test_weighted_cursor_is_incremental_and_deterministic():
    d, lens = _wparity_inputs(64, seed=9)
    c1 = rl.WeightedIndexCursor(d, lens)
    e1, i1 = c1.advance(256)
    c2 = rl.WeightedIndexCursor(d, lens)
    parts = [c2.advance(16), c2.advance(64), c2.advance(256)]
    e2 = np.concatenate([p[0] for p in parts])
    i2 = np.concatenate([p[1] for p in parts])
    assert sorted(zip(e1.tolist(), i1.tolist())) == \
        sorted(zip(e2.tolist(), i2.tolist()))
    # every element still participates at index 0 (weighting divides
    # the GAPS, it never skips the first cell)
    assert set(e1[i1 == 0].tolist()) == set(range(64))


def test_weighted_checksum_covers_the_length_word():
    d, lens = _wparity_inputs(8, seed=3)
    rows = rl.weighted_element_rows(d, lens)
    # perturb ONE length word: the checksum chain must notice
    bad = rows.copy()
    bad[0, 11] ^= 1
    w = rl.weighted_checksum_words(bad[:1, 3:11], bad[:1, 11])
    assert not (w == bad[:1, 1:3]).all()


def test_weighted_rows_reject_misaligned_or_oversize_lengths():
    d, _ = _wparity_inputs(4, seed=5)
    with pytest.raises(ValueError, match="align"):
        rl.weighted_element_rows(d, np.array([1, 2], np.int64))
    with pytest.raises(ValueError, match="u32"):
        rl.weighted_element_rows(d, np.array([1, 2, 3, 1 << 33]))
    with pytest.raises(ValueError, match=">= 0"):
        rl.weighted_element_rows(d, np.array([1, 2, 3, -1]))
