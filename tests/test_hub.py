"""ReplicationHub unit layer (ISSUE 8): admission, QoS, telemetry.

The chaos isolation proof lives in tests/test_hub_faults.py; this file
pins the mechanisms it relies on — structured admission rejection,
per-session windows, weighted-fair batch composition, shedding policy,
the flush barrier, and the per-session telemetry/collector plumbing the
oracle cross-checks.
"""

import hashlib
import threading
import time

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.hub import (
    HubBusy,
    HubError,
    ReplicationHub,
    SessionShed,
)

HARD_TIMEOUT = 30.0


def _h(p: bytes) -> bytes:
    return hashlib.blake2b(p, digest_size=32).digest()


def _hashlib_batch(payloads):
    return [_h(p) for p in payloads]


def _join_all(threads, timeout=HARD_TIMEOUT):
    for t in threads:
        t.join(timeout)
    assert all(not t.is_alive() for t in threads), "HANG"


# -- registration / admission -------------------------------------------------


def test_register_rejects_structured_when_session_cap_hit():
    with ReplicationHub(hash_batch=_hashlib_batch, max_sessions=2) as hub:
        a = hub.register("a")
        b = hub.register("b")
        with pytest.raises(HubBusy) as ei:
            hub.register("c")
        e = ei.value
        assert e.sessions == 2 and e.max_sessions == 2
        assert e.parked_bytes == 0 and e.parked_budget == hub.parked_budget
        a.close()
        # a released slot admits again — bounded state, not a latch
        c = hub.register("c")
        b.close()
        c.close()


def test_register_rejects_on_parked_budget(obs_enabled):
    from dat_replication_protocol_tpu.obs.events import EVENTS

    gate = threading.Event()

    def stuck_hash(payloads):
        gate.wait(HARD_TIMEOUT)
        return _hashlib_batch(payloads)

    hub = ReplicationHub(hash_batch=stuck_hash, parked_budget=500,
                         linger_s=0.0)
    try:
        s = hub.register("parker")
        # 300 parked bytes: past the admission threshold (budget // 2 —
        # admission closes BEFORE the shed cliff) but under the shed
        # budget itself, so the parked session survives while the
        # newcomer is refused.  submit() accounts synchronously, so no
        # settling wait is needed.
        s.submit(b"x" * 300, lambda d: None)
        with pytest.raises(HubBusy) as ei:
            hub.register("late")
        assert ei.value.parked_bytes >= 250
        rejects = EVENTS.events("hub.reject")
        assert rejects and rejects[-1]["fields"]["key"] == "late"
        assert obs_enabled.REGISTRY.counter("hub.rejected").value >= 1
    finally:
        gate.set()
        hub.close()


def test_duplicate_key_raises():
    with ReplicationHub(hash_batch=_hashlib_batch) as hub:
        s = hub.register("dup")
        with pytest.raises(ValueError):
            hub.register("dup")
        s.close()


# -- cross-session coalescing + correctness -----------------------------------


def test_many_sessions_coalesce_and_route_by_key():
    """N concurrent TpuDecoder sessions share ONE pipeline; every
    session's digest stream must be exactly its own (values pinned
    against hashlib), and the work must actually coalesce (fewer
    dispatched batches than total items)."""
    batches = []

    def recording_hash(payloads):
        batches.append(len(payloads))
        return _hashlib_batch(payloads)

    n_sessions, n_changes = 6, 40
    hub = ReplicationHub(hash_batch=recording_hash, linger_s=0.005)
    out: dict = {}

    def run_one(i):
        s = hub.register(f"k{i}")
        dec = protocol.decode(backend="tpu", pipeline=s)
        digs = []
        dec.on_digest(lambda kind, seq, d: digs.append((kind, seq, d)))
        e = protocol.encode()
        for j in range(n_changes):
            e.change({"key": f"s{i}-{j}", "change": j, "from": 0, "to": 1,
                      "value": b"v%d-%d" % (i, j)})
        b = e.blob(7)
        b.write(b"blob-%02d" % i)
        b.end()
        e.finalize()
        wire = b"".join(iter(lambda: e.read(4096) or b"", b""))
        for off in range(0, len(wire), 257):
            dec.write(wire[off:off + 257])
        dec.end()
        assert dec.finished
        out[i] = digs
        s.close()

    threads = [threading.Thread(target=run_one, args=(i,))
               for i in range(n_sessions)]
    for t in threads:
        t.start()
    _join_all(threads)
    hub.close()
    for i in range(n_sessions):
        digs = out[i]
        assert len(digs) == n_changes + 1
        # per-kind seqs are 0..n in order — delivery order preserved
        assert [s for k, s, _ in digs if k == "change"] == \
            list(range(n_changes))
        # values are THIS session's payload hashes, not a neighbor's
        from dat_replication_protocol_tpu.wire.change_codec import (
            encode_change,
        )

        for kind, seq, d in digs:
            if kind == "change":
                payload = encode_change({
                    "key": f"s{i}-{seq}", "change": seq, "from": 0,
                    "to": 1, "value": b"v%d-%d" % (i, seq),
                    "subset": None})
                assert d == _h(payload), (i, seq)
            else:
                assert d == _h(b"blob-%02d" % i)
    # coalescing happened: strictly fewer batches than items
    total_items = n_sessions * (n_changes + 1)
    assert sum(batches) == total_items
    assert len(batches) < total_items


def _wedged_hub(max_batch=16):
    """A hub whose dispatcher is deterministically parked inside its
    first device turn (one priming item), so tests can fill queues and
    call the composer directly without racing it."""
    entered = threading.Event()
    release = threading.Event()

    def gated_hash(payloads):
        entered.set()
        release.wait(HARD_TIMEOUT)
        return _hashlib_batch(payloads)

    hub = ReplicationHub(hash_batch=gated_hash, max_batch=max_batch,
                         linger_s=0.0)
    primer = hub.register("primer")
    primer.submit(b"prime", lambda d: None)
    assert entered.wait(5), "dispatcher never took the priming batch"
    return hub, release


def test_weighted_fair_batching_respects_weights():
    """With both queues saturated, one composed batch's per-session
    shares track the 3:1 weight ratio (quota pass), and spare budget is
    greedily filled (work-conserving)."""
    hub, release = _wedged_hub(max_batch=16)
    heavy = hub.register("heavy", weight=3.0)
    light = hub.register("light", weight=1.0)
    try:
        for i in range(40):
            heavy.submit(b"H" * 8, lambda d: None)
        for i in range(40):
            light.submit(b"L" * 8, lambda d: None)
        with hub._lock:
            batch = hub._compose_locked()
        by_key = {}
        for st, kind, item, cb, tag, nb in batch:
            by_key[st.key] = by_key.get(st.key, 0) + 1
        assert sum(by_key.values()) == 16
        # quota pass: 16 * 3/4 = 12 vs 16 * 1/4 = 4
        assert by_key["heavy"] == 12 and by_key["light"] == 4
    finally:
        release.set()
        hub.close()


def test_greedy_fill_is_work_conserving():
    hub, release = _wedged_hub(max_batch=16)
    heavy = hub.register("heavy", weight=3.0)
    light = hub.register("light", weight=1.0)
    try:
        for i in range(3):  # heavy has almost nothing queued
            heavy.submit(b"H", lambda d: None)
        for i in range(40):
            light.submit(b"L", lambda d: None)
        with hub._lock:
            batch = hub._compose_locked()
        by_key = {}
        for st, *_ in batch:
            by_key[st.key] = by_key.get(st.key, 0) + 1
        # light's surplus fills heavy's unused quota: full batch anyway
        assert sum(by_key.values()) == 16
        assert by_key == {"heavy": 3, "light": 13}
    finally:
        release.set()
        hub.close()


# -- windows / backpressure ---------------------------------------------------


def test_slow_consumer_stalls_only_its_own_window():
    """A session that submits without draining fills ITS window and its
    submit blocks; a co-resident session keeps completing unimpeded —
    the per-session QoS contract at the unit level."""
    hub = ReplicationHub(hash_batch=_hashlib_batch, window_items=8,
                         linger_s=0.0)
    slow = hub.register("slow")
    fast = hub.register("fast")
    fast_done = []
    blocked = threading.Event()
    proceed = threading.Event()

    def slow_run():
        # 8 fills the window; the 9th must park until completions drain
        # (which submit() does on entry) — park detection via timing
        for i in range(20):
            slow.submit(b"s" * 10, lambda d: proceed.wait(5))
            # the FIRST delivered completion parks inside the callback,
            # so the submit loop wedges behind its own consumer
            if i == 0:
                blocked.set()

    t_slow = threading.Thread(target=slow_run, daemon=True)
    t_slow.start()
    assert blocked.wait(5)

    def fast_run():
        for i in range(50):
            fast.submit(b"f%03d" % i, lambda d: fast_done.append(d))
        fast.flush()

    t_fast = threading.Thread(target=fast_run)
    t_fast.start()
    _join_all([t_fast], timeout=10)
    assert len(fast_done) == 50  # fast finished while slow sat parked
    proceed.set()
    _join_all([t_slow], timeout=10)
    slow.close()
    fast.close()
    hub.close()


def test_flush_is_a_per_session_barrier():
    hub = ReplicationHub(hash_batch=_hashlib_batch, linger_s=0.005)
    s = hub.register("flusher")
    got = []
    for i in range(100):
        s.submit(b"p%03d" % i, lambda d: got.append(d))
    s.flush()
    assert len(got) == 100
    assert got[7] == _h(b"p007")  # submit order preserved
    s.close()
    hub.close()


# -- shedding -----------------------------------------------------------------


def test_heaviest_offender_is_shed_first_and_neighbors_survive(obs_enabled):
    from dat_replication_protocol_tpu.obs.events import EVENTS

    release = threading.Event()

    def gated_hash(payloads):
        release.wait(HARD_TIMEOUT)
        return _hashlib_batch(payloads)

    hub = ReplicationHub(hash_batch=gated_hash, parked_budget=5_000,
                         window_items=10_000, window_bytes=10 << 20,
                         linger_s=0.0)
    flood = hub.register("flood")
    light = hub.register("light")
    light_got = []
    shed_seen = []

    def flood_run():
        try:
            for i in range(1000):
                flood.submit(b"x" * 100, lambda d: None)
        except SessionShed as e:
            shed_seen.append(e)

    t = threading.Thread(target=flood_run)
    t.start()
    _join_all([t], timeout=10)
    assert shed_seen, "over-budget flood was never shed"
    e = shed_seen[0]
    assert e.key == "flood" and e.reason == "parked-budget"
    assert e.parked_bytes > 5_000
    release.set()

    def light_run():
        for i in range(10):
            light.submit(b"y" * 10, lambda d: light_got.append(d))
        light.flush()

    t2 = threading.Thread(target=light_run)
    t2.start()
    _join_all([t2], timeout=10)
    assert len(light_got) == 10  # the neighbor never noticed
    sheds = EVENTS.events("hub.shed")
    assert len(sheds) == 1
    assert sheds[0]["fields"]["key"] == "flood"
    assert sheds[0]["fields"]["reason"] == "parked-budget"
    assert obs_enabled.REGISTRY.counter("hub.shed").value == 1
    # further use of the shed session raises the same structured error
    with pytest.raises(SessionShed):
        flood.submit(b"more", lambda d: None)
    with pytest.raises(SessionShed):
        flood.flush()
    flood.close()
    light.close()
    hub.close()


def test_dispatch_latency_shed_arm(obs_enabled):
    """The secondary policy arm: a slow device turn plus parked bytes
    past half budget sheds the heaviest offender."""
    from dat_replication_protocol_tpu.obs.events import EVENTS

    def slow_hash(payloads):
        time.sleep(0.05)
        return _hashlib_batch(payloads)

    hub = ReplicationHub(hash_batch=slow_hash, parked_budget=10_000,
                         latency_shed_s=0.01, window_items=10_000,
                         linger_s=0.0, max_batch=8)
    s = hub.register("bursty")
    try:
        with pytest.raises(SessionShed) as ei:
            for i in range(200):
                s.submit(b"z" * 80, lambda d: None)
                time.sleep(0.001)
        assert ei.value.reason in ("dispatch-latency", "parked-budget")
        assert EVENTS.events("hub.shed")
    finally:
        s.close()
        hub.close()


# -- lifecycle / failure ------------------------------------------------------


def test_engine_failure_surfaces_as_hub_error_everywhere(obs_enabled):
    from dat_replication_protocol_tpu.obs.events import EVENTS

    def broken_hash(payloads):
        raise RuntimeError("engine on fire")

    hub = ReplicationHub(hash_batch=broken_hash, linger_s=0.0)
    s = hub.register("victim")
    with pytest.raises(HubError):
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            s.submit(b"x", lambda d: None)
            time.sleep(0.005)
        pytest.fail("dispatcher failure never surfaced")
    errs = EVENTS.events("hub.error")
    assert errs and "engine on fire" in errs[0]["fields"]["error"]
    with pytest.raises(HubError):  # registration fails too
        hub.register("late")
    hub.close()


def test_close_makes_sessions_raise_hub_error():
    hub = ReplicationHub(hash_batch=_hashlib_batch)
    s = hub.register("orphan")
    hub.close()
    with pytest.raises(HubError):
        s.submit(b"x", lambda d: None)


# -- per-session telemetry (ISSUE 8 satellite) --------------------------------


def test_hub_sessions_gauge_and_collector_entries(obs_enabled):
    hub = ReplicationHub(hash_batch=_hashlib_batch, linger_s=0.002)
    a = hub.register("alpha")
    b = hub.register("beta")
    got = []
    for i in range(12):
        a.submit(b"a" * 50, lambda d: got.append(d))
    a.flush()
    snap = obs_enabled.REGISTRY.snapshot()
    assert snap["gauges"]["hub.sessions"] == 2.0
    # labeled per-session entries ride the snapshot via the collector
    assert snap["counters"]["hub.session.submitted{session=alpha}"] == 12
    assert snap["counters"]["hub.session.delivered{session=alpha}"] == 12
    assert snap["counters"]["hub.session.submitted{session=beta}"] == 0
    assert snap["gauges"]["hub.session.parked_bytes{session=alpha}"] == 0.0
    assert snap["counters"]["hub.session.dispatches{session=alpha}"] >= 1
    # sessions_snapshot is the same story keyed for --stats-fd lines
    per = hub.sessions_snapshot()
    assert per["alpha"]["submitted"] == 12
    assert per["alpha"]["delivered"] == 12
    assert per["alpha"]["shed"] is None
    a.close()
    snap2 = obs_enabled.REGISTRY.snapshot()
    # dead sessions drop out of the breakdown (bounded cardinality)
    assert "hub.session.submitted{session=alpha}" not in snap2["counters"]
    assert snap2["gauges"]["hub.sessions"] == 1.0
    b.close()
    hub.close()


def test_labeled_collector_entries_render_as_prom_labels(obs_enabled):
    from dat_replication_protocol_tpu.obs import metrics

    hub = ReplicationHub(hash_batch=_hashlib_batch)
    s = hub.register("p1")
    text = metrics.to_prom_text()
    assert 'dat_hub_session_parked_bytes{session="p1"} 0' in text
    assert "# TYPE dat_hub_sessions gauge" in text
    s.close()
    hub.close()


def test_mesh_sharded_hub_engine_matches_hashlib(monkeypatch):
    """The cross-session batch sharded over the 8-device virtual mesh
    (batch-dim NamedSharding): digests must be byte-identical to
    hashlib, routed back to the right sessions."""
    monkeypatch.setenv("DAT_DEVICE_HASH", "1")  # opt into the device path
    hub = ReplicationHub(mesh="auto", linger_s=0.01)
    a = hub.register("ma")
    b = hub.register("mb")
    got_a, got_b = [], []
    payloads_a = [b"mesh-a-%d" % i for i in range(10)]
    payloads_b = [b"mesh-b-%d" % i * 3 for i in range(7)]
    for p in payloads_a:
        a.submit(p, lambda d: got_a.append(d))
    for p in payloads_b:
        b.submit(p, lambda d: got_b.append(d))
    a.flush()
    b.flush()
    assert got_a == [_h(p) for p in payloads_a]
    assert got_b == [_h(p) for p in payloads_b]
    a.close()
    b.close()
    hub.close()


def test_register_rejects_label_breaking_keys():
    # keys ride telemetry label sets and JSON breakdowns: structural
    # characters would corrupt the exposition for EVERY session
    with ReplicationHub(hash_batch=_hashlib_batch) as hub:
        for bad in ("a,b", "a{b", "a}b", 'a"b', "a=b", "a\nb", ""):
            with pytest.raises(ValueError):
                hub.register(bad)
        ok = hub.register("tenant-a:10.0.0.7:4711")  # sidecar shape
        ok.close()


def test_stale_hub_close_keeps_successor_collector(obs_enabled):
    # rolling restart: hub B starts while hub A drains; A closing late
    # must not delete B's live collector entries
    hub_a = ReplicationHub(hash_batch=_hashlib_batch)
    hub_b = ReplicationHub(hash_batch=_hashlib_batch)  # replaces A's
    s = hub_b.register("survivor")
    hub_a.close()
    snap = obs_enabled.REGISTRY.snapshot()
    assert "hub.session.submitted{session=survivor}" in snap["counters"]
    s.close()
    hub_b.close()
