"""Every example runs to completion (subprocess, CPU backend).

Examples are documentation that executes; a broken one is a broken
quick-start.  Each runs in its own interpreter exactly as the docstring
instructs (JAX_PLATFORMS=cpu).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(
    f for f in os.listdir(os.path.join(REPO, "examples"))
    if f.endswith(".py")
)


@pytest.mark.parametrize("name", EXAMPLES)
def test_example_runs(name):
    # JAX_PLATFORMS=cpu alone is not reliable in a child on the dev
    # image (its sitecustomize re-forces the tunneled platform after
    # env is read — observed wedging the sidecar example's digest
    # dispatch); the routing layer's own overrides pin every engine to
    # the host path, which is what "CPU backend" means here anyway
    env = dict(os.environ, JAX_PLATFORMS="cpu", DAT_DEVICE_HASH="0",
               DAT_DEVICE_CDC="0", DAT_DEVICE_MERKLE="0")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", name)],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert out.returncode == 0, f"{name} failed:\n{out.stderr[-2000:]}"
    assert out.stdout.strip(), f"{name} produced no output"
