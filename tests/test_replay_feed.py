"""Replay engine (native + Python paths) and the batching feed layer."""

import hashlib
import random

import numpy as np
import pytest

from dat_replication_protocol_tpu.batch import feed
from dat_replication_protocol_tpu.ops.blake2b import pack_payloads
from dat_replication_protocol_tpu.runtime import native, replay
from dat_replication_protocol_tpu.wire.change_codec import Change, encode_change
from dat_replication_protocol_tpu.wire.framing import (
    TYPE_BLOB,
    TYPE_CHANGE,
    ProtocolError,
    frame,
)


def _sample_changes(n, seed=0):
    rng = random.Random(seed)
    out = []
    for i in range(n):
        out.append(
            Change(
                key=f"key-{i}",
                change=i,
                from_=rng.randrange(0, 1 << 32),
                to=rng.randrange(0, 1 << 32),
                value=rng.randbytes(rng.choice([0, 3, 200])) if rng.random() < 0.7 else None,
                subset=f"s{i % 3}" if rng.random() < 0.5 else None,
            )
        )
    return out


def _log(changes, blobs=()):
    parts = []
    bi = iter(blobs)
    for i, ch in enumerate(changes):
        parts.append(frame(TYPE_CHANGE, encode_change(ch)))
        if i % 3 == 0:
            b = next(bi, None)
            if b is not None:
                parts.append(frame(TYPE_BLOB, b))
    return b"".join(parts)


@pytest.fixture(params=["native", "python"])
def native_mode(request, monkeypatch):
    if request.param == "native":
        if not native.available():
            pytest.skip("no native toolchain")
    else:
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_tried", True)
    return request.param


def test_replay_roundtrip(native_mode):
    changes = _sample_changes(50, seed=1)
    blobs = [b"B" * n for n in (1, 200, 0, 5, 1000, 7, 9, 11, 13, 15, 17)]
    log = _log(changes, blobs)
    cols, frames = replay.replay_log(log)
    assert len(cols) == len(changes)
    for i, ch in enumerate(changes):
        got = cols.row(i)
        assert got.key == ch.key
        assert got.change == ch.change and got.from_ == ch.from_ and got.to == ch.to
        assert got.value == (ch.value if ch.value is not None else b"")
        assert got.subset == (ch.subset if ch.subset is not None else "")
    # blob extents preserved in order
    sel = frames.ids == TYPE_BLOB
    got_blobs = [
        bytes(frames.buf[s : s + l])
        for s, l in zip(frames.starts[sel], frames.lens[sel])
    ]
    assert got_blobs == blobs[: len(got_blobs)]


def test_replay_multibyte_varint_frames(native_mode):
    # payloads > 127 bytes force 2-byte frame varints
    changes = [
        Change(key="k" * 100, change=1, from_=0, to=1, value=b"v" * 300)
    ]
    cols, _ = replay.replay_log(_log(changes))
    assert cols.row(0).value == b"v" * 300


def test_replay_truncated_raises(native_mode):
    log = _log(_sample_changes(3))
    with pytest.raises(ProtocolError, match="truncated"):
        replay.split_frames(log[:-2])


def test_replay_partial_tail_streaming(native_mode):
    log = _log(_sample_changes(3))
    idx = replay.split_frames(log[:-2], allow_partial_tail=True)
    full = replay.split_frames(log)
    # all but the truncated last frame parsed; consumed stops exactly at
    # the truncated frame's header start
    assert len(idx) == 2
    assert idx.consumed == int(full.starts[1] + full.lens[1])
    assert np.array_equal(idx.starts, full.starts[:2])


def test_replay_unknown_type_raises(native_mode):
    log = frame(7, b"xx")
    with pytest.raises(ProtocolError, match="unknown type: 7"):
        replay.replay_log(log)


def test_replay_corrupt_record_raises(native_mode):
    log = frame(TYPE_CHANGE, b"\xff\xff\xff")
    with pytest.raises(ProtocolError, match="corrupt Change record at index 0"):
        replay.replay_log(log)


def test_replay_empty_framed_length_raises(native_mode):
    with pytest.raises(ProtocolError, match="framed length 0"):
        replay.split_frames(b"\x00")


def test_replay_hostile_huge_frame_length(native_mode):
    # 10-byte varint encoding 2^63: must not wrap negative in the native
    # splitter and walk backwards (OOB read).  Treated as a partial tail
    # in streaming mode, truncation error in strict mode — both paths.
    from dat_replication_protocol_tpu.wire.varint import encode_uvarint

    hostile = encode_uvarint(1 << 63) + bytes([TYPE_CHANGE]) + b"x" * 16
    with pytest.raises(ProtocolError, match="truncated"):
        replay.split_frames(hostile)
    idx = replay.split_frames(hostile, allow_partial_tail=True)
    assert len(idx) == 0 and idx.consumed == 0


def test_replay_hostile_huge_record_field_length(native_mode):
    # Change record whose `value` field claims a 2^63-byte length: the
    # native decoder must reject it (unsigned bounds check), not read OOB.
    from dat_replication_protocol_tpu.wire.varint import encode_uvarint

    payload = (
        bytes([(2 << 3) | 2, 1]) + b"k"  # key = "k"
        + bytes([(3 << 3) | 0, 1])  # change = 1
        + bytes([(4 << 3) | 0, 0])  # from = 0
        + bytes([(5 << 3) | 0, 1])  # to = 1
        + bytes([(6 << 3) | 2]) + encode_uvarint(1 << 63)  # value: huge len
    )
    log = frame(TYPE_CHANGE, payload)
    with pytest.raises(ProtocolError, match="corrupt Change record at index 0"):
        replay.replay_log(log)


def test_replay_overlong_varint_rejected(native_mode):
    # 10-byte varint whose 10th byte encodes bits >= 2^64: malformed on
    # both paths (native returns BAD_VARINT, Python raises ValueError).
    hostile = b"\x80" * 9 + b"\x7f" + bytes([TYPE_CHANGE]) + b"x"
    with pytest.raises(ProtocolError):
        replay.split_frames(hostile, allow_partial_tail=True)


def test_native_and_python_agree():
    if not native.available():
        pytest.skip("no native toolchain")
    changes = _sample_changes(30, seed=3)
    log = _log(changes, [b"blob-bytes"] * 10)
    buf = np.frombuffer(log, dtype=np.uint8)
    n_idx = replay.split_frames(buf)
    n_cols = replay.decode_change_columns(
        n_idx.buf, n_idx.starts[n_idx.ids == 1], n_idx.lens[n_idx.ids == 1]
    )
    try:
        native._lib, saved = None, native._lib
        p_idx = replay.split_frames(buf)
        p_cols = replay.decode_change_columns(
            p_idx.buf, p_idx.starts[p_idx.ids == 1], p_idx.lens[p_idx.ids == 1]
        )
    finally:
        native._lib = saved
    for f in ("starts", "lens", "ids"):
        assert np.array_equal(getattr(n_idx, f), getattr(p_idx, f))
    for f in ("change", "from_", "to", "key_off", "key_len", "sub_off",
              "sub_len", "val_off", "val_len"):
        assert np.array_equal(getattr(n_cols, f), getattr(p_cols, f)), f


# ---------------------------------------------------------------------------
# feed layer
# ---------------------------------------------------------------------------


def test_pack_ragged_matches_pack_payloads():
    rng = random.Random(4)
    payloads = [rng.randbytes(rng.choice([0, 1, 127, 128, 129, 300])) for _ in range(20)]
    buf = np.frombuffer(b"".join(payloads), dtype=np.uint8)
    lens = np.array([len(p) for p in payloads], dtype=np.int64)
    offs = np.cumsum(lens) - lens
    mh_a, ml_a, len_a = feed.pack_ragged(buf, offs, lens, nblocks=4)
    mh_b, ml_b, len_b = pack_payloads(payloads, nblocks=4)
    assert np.array_equal(mh_a, mh_b)
    assert np.array_equal(ml_a, ml_b)
    assert np.array_equal(len_a, len_b)


def test_hash_extents_matches_hashlib():
    rng = random.Random(5)
    payloads = [rng.randbytes(rng.choice([1, 50, 200, 2000])) for _ in range(17)]
    buf = np.frombuffer(b"".join(payloads), dtype=np.uint8)
    lens = np.array([len(p) for p in payloads], dtype=np.int64)
    offs = np.cumsum(lens) - lens
    got = feed.hash_extents(buf, offs, lens)
    exp = [hashlib.blake2b(p, digest_size=32).digest() for p in payloads]
    assert [got[i].tobytes() for i in range(len(payloads))] == exp


def test_leaves_from_columns_hash_framed_payloads():
    changes = _sample_changes(9, seed=6)
    log = _log(changes, [b"blobby"] * 3)
    cols, frames = replay.replay_log(log)
    leaves = feed.leaves_from_columns(cols, frames)
    exp = [
        hashlib.blake2b(encode_change(ch), digest_size=32).digest()
        for ch in changes
    ]
    # absent optionals re-encode identically (None vs '' both omitted)?
    # the framed bytes ARE the original encoding, so exact match:
    assert [leaves[i].tobytes() for i in range(len(changes))] == exp


def test_bucketed_extents():
    lens = np.array([0, 1, 128, 129, 500, 4000])
    buckets = feed.bucketed_extents(lens)
    assert sorted(buckets) == [1, 2, 4, 32]
    assert buckets[1].tolist() == [0, 1, 2]
    assert buckets[2].tolist() == [3]
    assert buckets[4].tolist() == [4]
    assert buckets[32].tolist() == [5]


def test_encode_change_log_matches_python_framing():
    import time

    from dat_replication_protocol_tpu.runtime.replay import (
        encode_change_log,
        replay_log,
    )
    from dat_replication_protocol_tpu.wire.change_codec import encode_change
    from dat_replication_protocol_tpu.wire.framing import TYPE_CHANGE, frame

    records = [
        {"key": f"k{i}", "change": i, "from": i, "to": i + 1,
         "value": b"v" * (i % 20) if i % 2 else None,
         "subset": "s%d" % i if i % 3 else None}
        for i in range(500)
    ]
    # byte-identical to the scalar Python framing
    exp = b"".join(
        frame(TYPE_CHANGE, encode_change(r)) for r in records
    )
    got = encode_change_log(records)
    assert got == exp

    # and replayable: the inverse round-trips
    cols, frames = replay_log(got)
    assert len(cols) == 500
    assert cols.row(7).key == "k7"
    assert cols.row(7).value == b"v" * 7

    # rate sanity: bulk encode of 50k rows stays well under a second
    big = records * 100
    t0 = time.perf_counter()
    wire = encode_change_log(big)
    dt = time.perf_counter() - t0
    assert len(wire) == len(exp) * 100
    assert dt < 5.0, f"bulk encode too slow: {dt:.2f}s for {len(big)} rows"


def test_encode_change_log_python_fallback_identical(monkeypatch):
    from dat_replication_protocol_tpu.runtime import native, replay

    records = [{"key": "a", "change": 1, "from": 0, "to": 1, "value": b"zz"},
               {"key": "b", "change": 2, "from": 1, "to": 2, "subset": "s"}]
    with_native = replay.encode_change_log(records)
    monkeypatch.setattr(native, "get_lib", lambda: None)
    without = replay.encode_change_log(records)
    assert with_native == without


def test_encode_change_columns_roundtrips_byte_exact(monkeypatch):
    # wire -> replay_log -> encode_change_columns must reproduce the
    # change frames byte-for-byte (native and Python paths both)
    from dat_replication_protocol_tpu.runtime import replay
    from dat_replication_protocol_tpu.wire.change_codec import Change, encode_change
    from dat_replication_protocol_tpu.wire.framing import TYPE_CHANGE, frame

    recs = [
        Change(key=f"k{i}", change=i, from_=i, to=i + 1,
               value=(b"v%d" % i) * (i % 7) if i % 3 else None,
               subset="" if i % 5 == 0 else ("s%d" % i if i % 2 else None))
        for i in range(500)
    ]
    wire = b"".join(frame(TYPE_CHANGE, encode_change(c)) for c in recs)
    cols, _ = replay.replay_log(np.frombuffer(wire, np.uint8))
    assert replay.encode_change_columns(cols) == wire
    # Python fallback path agrees
    monkeypatch.setattr(replay.native, "get_lib", lambda: None)
    assert replay.encode_change_columns(cols) == wire


def test_encode_change_columns_mixed_log_keeps_changes_only():
    from dat_replication_protocol_tpu.runtime import replay
    from dat_replication_protocol_tpu.wire.change_codec import Change, encode_change
    from dat_replication_protocol_tpu.wire.framing import TYPE_BLOB, TYPE_CHANGE, frame

    c1 = frame(TYPE_CHANGE, encode_change(Change(key="a", change=1, from_=0, to=1)))
    blob = frame(TYPE_BLOB, b"\x01\x02\x03\x04")
    c2 = frame(TYPE_CHANGE, encode_change(Change(key="b", change=2, from_=1, to=2)))
    cols, _ = replay.replay_log(np.frombuffer(c1 + blob + c2, np.uint8))
    assert replay.encode_change_columns(cols) == c1 + c2
    empty_cols, _ = replay.replay_log(np.frombuffer(blob, np.uint8))
    assert replay.encode_change_columns(empty_cols) == b""


def test_parallel_decode_matches_serial_and_reports_first_error(monkeypatch):
    """dat_decode_changes_mt must produce identical columns to the serial
    path and report the FIRST corrupt record index even when a later
    thread's range also holds corruption."""
    import numpy as np
    import pytest

    from dat_replication_protocol_tpu.runtime import native, replay
    from dat_replication_protocol_tpu.wire.change_codec import encode_change
    from dat_replication_protocol_tpu.wire.framing import TYPE_CHANGE, frame

    if not native.available():
        pytest.skip("native library unavailable")
    monkeypatch.setenv("DAT_NTHREADS", "4")  # force the fan-out path
    recs = [frame(TYPE_CHANGE, encode_change({
        "key": f"k{i}", "change": i, "from": i, "to": i + 1,
        "value": b"v" * (i % 7),
    })) for i in range(20_000)]
    buf = np.frombuffer(b"".join(recs), np.uint8)
    cols, frames = replay.replay_log(buf)
    assert len(cols) == 20_000
    assert cols.row(12_345).key == "k12345"

    # corrupt two records in different thread ranges; the reported index
    # must be the earlier one
    offs = np.cumsum([len(r) for r in recs])
    mutable = bytearray(b"".join(recs))
    for victim in (5_000, 15_000):
        start = offs[victim - 1] if victim else 0
        mutable[start + 2] = 0x07  # wire-type 7: invalid
    bad = np.frombuffer(bytes(mutable), np.uint8)
    fi = replay.split_frames(bad)
    with pytest.raises(replay.ProtocolError, match="index 5000"):
        replay.decode_change_columns(bad, fi.starts, fi.lens)


def test_parallel_encode_byte_identical(monkeypatch):
    """dat_encode_changes_mt (size pass + prefix sum + parallel write)
    must be byte-identical to the serial encoder and to the per-record
    Python codec, across absent/present-empty optionals."""
    import numpy as np
    import pytest

    from dat_replication_protocol_tpu.runtime import native, replay
    from dat_replication_protocol_tpu.wire.change_codec import encode_change
    from dat_replication_protocol_tpu.wire.framing import TYPE_CHANGE, frame

    if not native.available():
        pytest.skip("native library unavailable")
    monkeypatch.setenv("DAT_NTHREADS", "4")
    recs = []
    for i in range(30_000):
        r = {"key": f"key-{i}", "change": i, "from": i, "to": i + 1}
        if i % 3 == 0:
            r["value"] = b"v" * (i % 11)  # incl. present-empty at i%11==0
        if i % 5 == 0:
            r["subset"] = "s" * (i % 4)
        recs.append(r)
    expected = b"".join(frame(TYPE_CHANGE, encode_change(r)) for r in recs)
    cols, _ = replay.replay_log(np.frombuffer(expected, np.uint8))
    assert replay.encode_change_columns(cols) == expected
    assert replay.encode_change_log(recs) == expected
