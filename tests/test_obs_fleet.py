"""Fleet observability plane (ISSUE 11): watermarks, endpoint, aggregator.

Layers under test:

* **Watermark exactness** — the exported cursors ARE the journal's and
  decoder's byte counts, not approximations: gauge == ``journal.end``,
  gauge == ``decoder._parsed``, at any instant.
* **The lag join** — ``append − parsed`` in bytes, clock-free seconds
  from the sender's marks ring (the aggregator never compares two
  machines' clocks).
* **The chaos oracle** (acceptance): a 20-seed sweep where a live
  sender outpaces a receiver running through the PR 2 fault injector —
  the aggregator's reported lag must match ground truth reconstructed
  from journal/decoder state at EVERY poll, rise while the fault holds
  the receiver back, fall after resume, and end at EXACTLY zero when
  the decoded session matches (plus a 100-seed slow soak).
* **The scrape endpoint** — all four routes, read-only-ness (a
  continuous scraper changes nothing and costs the hot path nothing
  measurable), the disabled-gate dark path, staged /healthz.
* **SLO gate** — ``fleet --check`` exit codes: pass, doctored-fail,
  malformed-SLO; this file IS the tier-1 live gate (the 2-replica
  in-process scenario runs un-slow-marked).
* **N-log timeline** — the offline mirror: 3-log golden merge clean,
  doctored gap flagged.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.obs.fleet import (
    FleetTarget,
    FleetView,
    evaluate_slo,
    load_slo,
    render_dashboard,
    run_fleet_check,
)
from dat_replication_protocol_tpu.obs.http import (
    ObsHttpServer,
    default_healthz,
    default_snapshot,
)
from dat_replication_protocol_tpu.obs.watermarks import WATERMARKS, link_lag
from dat_replication_protocol_tpu.session.faults import FaultPlan, FaultyReader
from dat_replication_protocol_tpu.session.reconnect import (
    BackoffPolicy,
    run_resumable,
)
from dat_replication_protocol_tpu.session.resume import WireJournal
from dat_replication_protocol_tpu.wire.framing import ProtocolError

HARD_TIMEOUT = 30.0


def _with_watchdog(fn):
    box: dict = {}

    def run():
        try:
            box["ret"] = fn()
        except BaseException as e:  # noqa: BLE001 — relayed to the test
            box["err"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(HARD_TIMEOUT)
    assert not t.is_alive(), f"HANG: still running after {HARD_TIMEOUT}s"
    if "err" in box:
        raise box["err"]
    return box["ret"]


def _build_wire(rows: int = 40) -> bytes:
    e = protocol.encode()
    j = WireJournal()
    e.attach_journal(j)
    for i in range(rows):
        e.change({"key": f"k-{i:04d}", "change": i, "from": i, "to": i + 1,
                  "value": b"v" * (i % 23)})
    b = e.blob(64)
    b.write(b"x" * 64)
    b.end()
    e.finalize()
    while e.read(4096) is not None:
        pass
    return j.read_from(0)


def _expected_events(wire: bytes) -> list:
    dec = protocol.decode()
    events: list = []
    dec.change(lambda c, done: (events.append(("change", c.key, c.value)),
                                done()))
    dec.blob(lambda b, done: b.collect(
        lambda data: (events.append(("blob", data)), done())))
    dec.write(wire)
    dec.end()
    assert dec.finished
    return events


class _Follower:
    """Blocking reader over a growing journal — the live-replication
    transport for the in-process fleet (reads block until the producer
    appends past the cursor or declares EOF)."""

    def __init__(self, journal: WireJournal, start: int,
                 done: threading.Event):
        self._j = journal
        self._pos = start
        self._done = done

    def read(self, n: int) -> bytes:
        while True:
            if self._j.end > self._pos:
                data = bytes(self._j.read_from(self._pos)[:n])
                self._pos += len(data)
                return data
            if self._done.is_set() and self._j.end <= self._pos:
                return b""
            time.sleep(0.0005)


# -- watermark exactness ------------------------------------------------------


def test_watermark_gauges_are_exactly_the_journal_byte_counts(obs_enabled):
    j = WireJournal()
    j.watermark("wm-x")
    j.append(b"a" * 100)
    j.append(b"b" * 55)
    j.attach_reader("r", 0)
    j.ack(60, reader="r")
    gauges = obs_enabled.REGISTRY.snapshot()["gauges"]
    assert gauges["session.wire.offset{link=wm-x,role=append}"] == 155.0
    assert gauges["session.wire.offset{link=wm-x,role=acked}"] == 60.0
    assert gauges["session.wire.offset{link=wm-x,role=append}"] == float(
        j.end)
    # marks recorded one per append, monotone offsets
    snap = WATERMARKS.snapshot()["links"]["wm-x"]
    assert [m[0] for m in snap["marks"]] == [100, 155]
    WATERMARKS.untrack("wm-x")
    assert "wm-x" not in WATERMARKS.snapshot()["links"]


def test_decoder_watermarks_track_parsed_and_checkpoint(obs_enabled):
    wire = _build_wire(8)
    dec = protocol.decode()
    dec.watermark("wm-d")
    dec.change(lambda c, done: done())
    dec.blob(lambda b, done: b.collect(lambda _d: done()))
    half = len(wire) // 2
    dec.write(wire[:half])
    snap = WATERMARKS.snapshot()["links"]["wm-d"]["offsets"]
    assert snap["accepted"] == dec.bytes == half
    assert snap["parsed"] == dec._parsed <= half
    assert snap["checkpoint"] == 0  # no checkpoint exported yet
    ckpt = dec.checkpoint()
    snap = WATERMARKS.snapshot()["links"]["wm-d"]["offsets"]
    assert snap["checkpoint"] == ckpt.wire_offset == half
    dec.write(wire[half:])
    dec.end()
    assert dec.finished
    snap = WATERMARKS.snapshot()["links"]["wm-d"]["offsets"]
    assert snap["parsed"] == snap["accepted"] == len(wire)
    WATERMARKS.untrack("wm-d")


def test_link_label_rejects_structural_characters(obs_enabled):
    for bad in ("", "a,b", "a=b", 'a"b', "a\nb", "{x}"):
        with pytest.raises(ValueError):
            WATERMARKS.track("append", bad, lambda: 0)
    with pytest.raises(ValueError):
        WATERMARKS.track("", "ok-link", lambda: 0)


def test_marks_only_link_is_a_clock_source_not_a_half_link(obs_enabled):
    """The fan-out shared publish ring is marks-only (no cursors): it
    must NOT export as a joinable link, or the SLO gate would fail a
    healthy fan-out fleet on a link that can never join (review
    regression)."""
    WATERMARKS.mark("wm-clock", 100)
    assert "wm-clock" not in WATERMARKS.snapshot()["links"]
    # ...but a per-peer link aliasing it still resolves its marks
    WATERMARKS.track("append", "wm-peer", lambda: 100,
                     marks_from="wm-clock")
    WATERMARKS.track("delivered", "wm-peer", lambda: 40)
    rec = WATERMARKS.snapshot()["links"]["wm-peer"]
    assert [m[0] for m in rec["marks"]] == [100]
    assert rec["lag_bytes"] == 60 and rec["lag_seconds"] is not None
    # the SLO gate sees only real links
    view = FleetView([default_snapshot])
    rows = evaluate_slo({"require_converged": True}, view.poll())
    assert {r["subject"] for r in rows} == {"wm-peer"}
    WATERMARKS.untrack("wm-peer")
    WATERMARKS.untrack("wm-clock")


def test_outrun_marks_ring_never_understates_age(obs_enabled):
    """When older marks were evicted and the first retained mark is
    already past the receive frontier, the true age is OLDER than
    anything attributable — the join must say unknown (None), never a
    too-young number an SLO bound would wrongly pass (review
    regression)."""
    marks = [(500, 11.0), (1000, 12.5)]
    # nothing dropped: first-mark attribution is exact
    assert link_lag({"append": 1000, "parsed": 100}, marks, 13.0,
                    marks_dropped=0)[1] == pytest.approx(2.0)
    # ring outrun: the frontier byte predates every retained mark
    assert link_lag({"append": 1000, "parsed": 100}, marks, 13.0,
                    marks_dropped=7)[1] is None
    # dropped marks but a retained predecessor covers the frontier:
    # still exact
    assert link_lag({"append": 1000, "parsed": 600}, marks, 13.0,
                    marks_dropped=7)[1] == pytest.approx(0.5)


def test_dying_cursor_goes_missing_not_fatal(obs_enabled):
    WATERMARKS.track("append", "wm-dead", lambda: 1 // 0)
    WATERMARKS.track("acked", "wm-dead", lambda: 7)
    offs = WATERMARKS.snapshot()["links"]["wm-dead"]["offsets"]
    assert offs == {"acked": 7}  # the raising cursor vanished, quietly
    WATERMARKS.untrack("wm-dead")


# -- the lag join -------------------------------------------------------------


def test_link_lag_join_bytes_and_clock_free_seconds():
    offsets = {"append": 1000, "parsed": 400}
    marks = [(300, 10.0), (500, 11.0), (1000, 12.5)]
    lag_b, lag_s = link_lag(offsets, marks, now=13.0)
    assert lag_b == 600
    # oldest unparsed byte: first mark past 400 is (500, 11.0) -> 2.0s
    assert lag_s == pytest.approx(2.0)
    assert link_lag({"append": 5, "parsed": 5}, marks, 13.0) == (0, 0.0)
    assert link_lag({"append": 5}, marks, 13.0) == (None, None)
    # behind but no covering mark: bytes exact, age honestly unknown
    assert link_lag({"append": 9, "parsed": 1}, [], 13.0) == (8, None)


def test_fleet_join_across_two_targets_uses_sender_clock():
    # sender and receiver snapshots come from DIFFERENT processes with
    # different monotonic bases — the join must use the sender's
    sender_snap = {"watermarks": {"monotonic": 107.0, "links": {
        "L": {"offsets": {"append": 900},
              "marks": [[450, 100.0], [900, 106.0]]}}}}
    receiver_snap = {"watermarks": {"monotonic": 55512.0, "links": {
        "L": {"offsets": {"parsed": 440}, "marks": []}}}}
    view = FleetView([FleetTarget(lambda: sender_snap, name="sender"),
                      FleetTarget(lambda: receiver_snap, name="receiver")])
    sample = view.poll()
    entry = sample["links"]["L"]
    assert entry["lag_bytes"] == 460
    # first mark past 440 is (450, t=100.0) on the sender clock 107.0
    assert entry["lag_seconds"] == pytest.approx(7.0)
    assert sorted(entry["targets"]) == ["receiver", "sender"]


def test_fleet_drain_rate_from_history_ring():
    lag = {"v": 1000}
    t0 = {"v": 0}

    def snap():
        return {"watermarks": {"monotonic": 1.0, "links": {
            "L": {"offsets": {"append": 1000, "parsed": 1000 - lag["v"]},
                  "marks": []}}}}

    view = FleetView([snap])
    view.poll()
    lag["v"] = 0
    time.sleep(0.05)
    sample = view.poll()
    assert sample["links"]["L"]["lag_bytes"] == 0
    assert sample["links"]["L"]["drain_bps"] > 0  # lag shrank -> draining
    assert len(view.history("L")) == 2


# -- chaos oracle (acceptance) ------------------------------------------------

_CHAOS_WIRE = _build_wire(40)
_CHAOS_EXPECTED = _expected_events(_CHAOS_WIRE)


def _chaos_seed(seed: int):
    """One live replication run under an injected fault: producer
    appends the prebuilt wire into a watermarked journal in timed
    chunks; the receiver follows through FaultyReader; the aggregator
    polls throughout.  Returns (samples, stats, events, journal, dec)."""
    wire = _CHAOS_WIRE
    scenario = ("stall", "truncate")[seed % 2]
    at = 64 + (seed * 97) % (len(wire) // 2)

    j = WireJournal()
    j.watermark("chaos")
    dec = protocol.decode()
    dec.watermark("chaos")
    events: list = []
    dec.change(lambda c, done: (events.append(("change", c.key, c.value)),
                                done()))
    dec.blob(lambda b, done: b.collect(
        lambda data: (events.append(("blob", data)), done())))

    done_evt = threading.Event()

    def produce():
        step = 192
        for off in range(0, len(wire), step):
            j.append(wire[off:off + step])
            time.sleep(0.001)
        done_evt.set()

    def source(ckpt, failures):
        if failures == 0:
            if scenario == "stall":
                plan = FaultPlan(seed=seed, stall_at=max(0, at - 32),
                                 stall_s=0.06)
            else:
                plan = FaultPlan(seed=seed, truncate_at=at)
        else:
            plan = FaultPlan(seed=seed)  # clean resume connection
        return FaultyReader(_Follower(j, ckpt.wire_offset, done_evt).read, plan)

    view = FleetView([default_snapshot])
    samples: list = []
    producer = threading.Thread(target=produce, daemon=True)
    result: dict = {}

    def drive():
        result["stats"] = run_resumable(
            source, dec, BackoffPolicy(base=0.0005, cap=0.005,
                                       max_retries=8, seed=seed),
            chunk_size=512, expected_total=len(wire),
            stall_timeout=HARD_TIMEOUT / 2)

    driver = threading.Thread(target=drive, daemon=True)
    producer.start()
    # let the producer run ahead before the receiver starts: the sweep
    # must OBSERVE lag, not race the poll loop against a sub-ms drain
    time.sleep(0.004)
    driver.start()
    deadline = time.monotonic() + HARD_TIMEOUT
    while driver.is_alive():
        assert time.monotonic() < deadline, "HANG: chaos run stuck"
        samples.append(view.poll())
        time.sleep(0.002)
    driver.join()
    producer.join(timeout=5)
    samples.append(view.poll())  # the terminal sample
    WATERMARKS.untrack("chaos")
    return samples, result.get("stats"), events, j, dec


@pytest.mark.parametrize("seed", range(20))
def test_chaos_sweep_lag_matches_ground_truth_at_every_poll(
        seed, obs_enabled):
    samples, stats, events, j, dec = _chaos_seed(seed)
    assert stats is not None, "resumable fault class must converge"

    lags = []
    for s in samples:
        entry = s["links"].get("chaos")
        if entry is None or entry.get("lag_bytes") is None:
            continue
        offs = entry["offsets"]
        # ORACLE: the aggregator's number is exactly the watermark
        # identity — no smoothing, no estimation, no fabrication
        assert entry["lag_bytes"] == max(
            0, offs["append"] - offs["parsed"])
        lags.append(entry["lag_bytes"])

    # the fault held the receiver back while the producer kept
    # appending: lag must have visibly risen...
    assert lags and max(lags) > 0, "no lag ever observed under fault"
    # ...and fallen back to EXACTLY zero at convergence
    assert lags[-1] == 0
    final = samples[-1]["links"]["chaos"]
    assert final["lag_seconds"] == 0.0
    # ground truth from journal + decoder state, independently of the
    # watermark plane: everything produced was parsed
    assert j.end == len(_CHAOS_WIRE)
    assert dec._parsed == dec.bytes == j.end
    assert dec.finished
    # ...and the decoded session is byte-identical (digests match)
    assert events == _CHAOS_EXPECTED
    # injector ground truth: truncate scenarios resumed, reconnects
    # match the recorded faults exactly
    assert stats["reconnects"] == len(stats["faults"])


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(100))
def test_chaos_soak_lag_oracle(seed, obs_enabled):
    samples, stats, events, j, dec = _chaos_seed(seed)
    assert stats is not None
    final = samples[-1]["links"]["chaos"]
    assert final["lag_bytes"] == 0 and final["lag_seconds"] == 0.0
    assert events == _CHAOS_EXPECTED
    assert dec._parsed == j.end == len(_CHAOS_WIRE)


def test_chaos_flip_is_one_structured_error_never_wrong_lag(obs_enabled):
    """Corruption is not resumable: a flipped header byte must surface
    as ONE structured ProtocolError (the PR 2 contract) — and the
    watermark plane must keep reporting the honest join right through
    the failure, never a fabricated zero."""
    wire = _CHAOS_WIRE
    j = WireJournal()
    j.watermark("flip")
    dec = protocol.decode()
    dec.watermark("flip")
    dec.change(lambda c, done: done())
    dec.blob(lambda b, done: b.collect(lambda _d: done()))
    done_evt = threading.Event()
    j.append(wire)
    done_evt.set()

    def source(ckpt, failures):
        plan = FaultPlan(seed=1, flip_at=0, flip_mask=0x01) \
            if failures == 0 else FaultPlan(seed=1)
        return FaultyReader(_Follower(j, ckpt.wire_offset, done_evt).read, plan)

    view = FleetView([default_snapshot])
    with pytest.raises(ProtocolError) as ei:
        _with_watchdog(lambda: run_resumable(
            source, dec, BackoffPolicy(base=0.0005, cap=0.005,
                                       max_retries=3, seed=1),
            chunk_size=512, expected_total=len(wire),
            stall_timeout=HARD_TIMEOUT / 4))
    assert ei.value.offset is not None  # structured, with coordinates
    sample = view.poll()
    entry = sample["links"]["flip"]
    offs = entry["offsets"]
    assert entry["lag_bytes"] == max(0, offs["append"] - offs["parsed"])
    WATERMARKS.untrack("flip")


# -- the scrape endpoint ------------------------------------------------------


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read()


def test_endpoint_routes_serve_the_same_snapshot(obs_enabled):
    j = WireJournal()
    j.watermark("ep-link")
    j.append(b"z" * 77)
    with ObsHttpServer(0) as srv:
        status, body = _get(srv.url + "/snapshot")
        assert status == 200
        snap = json.loads(body)
        assert snap["watermarks"]["links"]["ep-link"]["offsets"][
            "append"] == 77
        status, body = _get(srv.url + "/metrics")
        assert status == 200
        text = body.decode()
        assert 'dat_session_wire_offset{link="ep-link",role="append"} 77' \
            in text
        status, body = _get(srv.url + "/healthz")
        assert status == 200 and json.loads(body)["ok"] is True
        status, body = _get(srv.url + "/events?n=5")
        assert status == 200
        status, _body = _get(srv.url + "/metrics/")  # trailing slash ok
        assert status == 200
    WATERMARKS.untrack("ep-link")


def test_endpoint_unknown_route_404(obs_enabled):
    with ObsHttpServer(0) as srv:
        try:
            urllib.request.urlopen(srv.url + "/nope", timeout=10)
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404


def test_healthz_degrades_to_503_when_admission_closed(obs_enabled):
    closed = {"open": False, "sessions": 9, "max_sessions": 9}
    with ObsHttpServer(0, admission_fn=lambda: closed) as srv:
        try:
            urllib.request.urlopen(srv.url + "/healthz", timeout=10)
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            rec = json.loads(e.read())
            assert rec["ok"] is False
            assert rec["stages"]["admission"]["ok"] is False


def test_healthz_stages_mirror_watchdog_and_hub_state(obs_enabled):
    from dat_replication_protocol_tpu.hub import ReplicationHub
    from dat_replication_protocol_tpu.obs.events import emit

    hub = ReplicationHub(hash_batch=lambda items: [b"\0" * 32 for _ in items],
                         max_sessions=4)
    try:
        hz = default_healthz(hub.admission_state)
        assert hz["ok"] is True
        assert hz["stages"]["admission"]["sessions"] == 0
        assert hz["stages"]["backend_init"]["state"] == "idle"
        emit("backend.init.stage", stage="first_compile", elapsed_s=1.0)
        hz = default_healthz(hub.admission_state)
        assert hz["stages"]["backend_init"]["state"] == "in-progress"
        emit("backend.init.stuck", stage="first_compile", elapsed_s=99.0)
        hz = default_healthz(hub.admission_state)
        assert hz["ok"] is False
        assert hz["stages"]["backend_init"]["state"] == "stuck"
        emit("backend.init.done", elapsed_s=100.0, stages=3, stuck=True)
        hz = default_healthz(hub.admission_state)
        assert hz["ok"] is True  # done AFTER stuck: init recovered
    finally:
        hub.close()


def test_scraping_is_read_only_and_costs_nothing_measurable(obs_enabled):
    """The overhead-budget proof: (a) 50 scrapes leave every counter
    value byte-identical — the endpoint reads locked snapshots, it
    never mutates; (b) decoding under two continuous scrapers stays
    within a COARSE wall-clock budget of the unscraped decode (the
    existing disabled-path budget test discipline: generous bound,
    CI-noise tolerant, catches a scraper that takes session locks or
    serializes the hot path)."""
    wire = _build_wire(200)

    def decode_once():
        dec = protocol.decode()
        dec.change(lambda c, done: done())
        dec.blob(lambda b, done: b.collect(lambda _d: done()))
        t0 = time.perf_counter()
        for off in range(0, len(wire), 1024):
            dec.write(wire[off:off + 1024])
        dec.end()
        assert dec.finished
        return time.perf_counter() - t0

    decode_once()  # warmup
    base = min(decode_once() for _ in range(3))

    with ObsHttpServer(0) as srv:
        before = json.loads(_get(srv.url + "/snapshot")[1])["metrics"]
        for _ in range(50):
            _get(srv.url + "/metrics")
            _get(srv.url + "/snapshot")
        after = json.loads(_get(srv.url + "/snapshot")[1])["metrics"]
        assert after["counters"] == before["counters"]  # read-only

        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                try:
                    _get(srv.url + "/snapshot")
                except OSError:
                    pass

        threads = [threading.Thread(target=hammer, daemon=True)
                   for _ in range(2)]
        for t in threads:
            t.start()
        try:
            scraped = min(decode_once() for _ in range(3))
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
    # coarse: scraping must not serialize the decode path.  4x absorbs
    # CI noise while still catching a lock-coupled endpoint.
    assert scraped < base * 4 + 0.05, (
        f"decode {base * 1e3:.2f}ms alone vs {scraped * 1e3:.2f}ms "
        f"under continuous scraping")


def test_endpoint_dark_gate_serves_but_hot_path_stays_dark():
    """Gate off: the endpoint still answers (zeros are an honest
    answer) but the session hot path emits nothing — scraping must not
    silently enable telemetry."""
    from dat_replication_protocol_tpu.obs import events, metrics

    assert not metrics.OBS.on  # the suite default outside obs_enabled
    metrics.REGISTRY.reset()
    events.EVENTS.clear()
    wire = _build_wire(10)
    with ObsHttpServer(0) as srv:
        dec = protocol.decode()
        dec.change(lambda c, done: done())
        dec.blob(lambda b, done: b.collect(lambda _d: done()))
        for _ in range(3):
            _get(srv.url + "/metrics")
        dec.write(wire)
        dec.end()
        status, body = _get(srv.url + "/snapshot")
        snap = json.loads(body)
    assert not metrics.OBS.on, "scraping flipped the gate on"
    assert snap["metrics"]["counters"].get("decoder.bytes", 0) == 0
    assert events.EVENTS.events() == []
    metrics.REGISTRY.reset()


# -- stats-fd / endpoint / driver oracle + emit_seq ---------------------------


def test_emitter_endpoint_and_driver_agree_on_watermarks(
        obs_enabled, tmp_path):
    from dat_replication_protocol_tpu.sidecar import (
        StatsEmitter,
        snapshot_stats,
    )

    wire = _build_wire(12)
    j = WireJournal()
    j.watermark("oracle")
    j.append(wire)
    dec = protocol.decode()
    dec.watermark("oracle")
    dec.change(lambda c, done: done())
    dec.blob(lambda b, done: b.collect(lambda _d: done()))
    dec.write(wire)
    dec.end()
    assert dec.finished

    out = tmp_path / "stats.jsonl"
    fd = os.open(str(out), os.O_WRONLY | os.O_CREAT)
    try:
        emitter = StatsEmitter(fd, interval=3600)
        assert emitter.dump_once()
        assert emitter.dump_once()
    finally:
        os.close(fd)
    lines = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert [ln["emit_seq"] for ln in lines] == [0, 1]  # monotonic

    with ObsHttpServer(0, snapshot_fn=snapshot_stats) as srv:
        endpoint = json.loads(_get(srv.url + "/snapshot")[1])
    file_wm = lines[-1]["watermarks"]["links"]["oracle"]["offsets"]
    http_wm = endpoint["watermarks"]["links"]["oracle"]["offsets"]
    # all three surfaces agree with the driver's own cursors
    truth = {"append": j.end, "acked": j.start, "accepted": dec.bytes,
             "parsed": dec._parsed, "checkpoint": dec._ckpt_offset}
    assert file_wm == truth
    assert http_wm == truth
    assert lines[-1]["watermarks"]["links"]["oracle"]["lag_bytes"] == 0
    WATERMARKS.untrack("oracle")


def test_file_target_detects_dropped_lines_via_emit_seq(
        obs_enabled, tmp_path):
    path = tmp_path / "t.jsonl"

    def line(seq, append):
        return json.dumps({"emit_seq": seq, "metrics": {}, "watermarks": {
            "monotonic": 1.0, "links": {"L": {
                "offsets": {"append": append, "parsed": append},
                "marks": []}}}}) + "\n"

    path.write_text(line(0, 10))
    target = FleetTarget(str(path))
    assert target.poll() is not None
    assert target.dropped_lines == 0
    # the emitter consumed seqs 1 and 2 for lines this file never got
    path.write_text(line(0, 10) + line(3, 30))
    assert target.poll() is not None
    assert target.dropped_lines == 2
    # a torn final line is skipped, not fatal
    with open(path, "a") as f:
        f.write('{"emit_seq": 4, "watermarks": {"links"')
    assert target.poll() is not None  # still the seq-3 line


def test_unreachable_target_is_visible_not_fatal(tmp_path):
    view = FleetView([str(tmp_path / "missing.jsonl")])
    sample = view.poll()
    assert sample["links"] == {}
    assert "missing.jsonl" in sample["errors"]
    rows = evaluate_slo({"max_shed": 0}, sample)
    assert any(r["check"] == "reachable" and r["status"] == "fail"
               for r in rows)


# -- event-loop lag SLO (ISSUE 18) --------------------------------------------


def _tracked_loop(name: str, lag_turn_s: float):
    """One profiler on the board with a single finished turn of
    ``lag_turn_s`` non-poll work (tick 0.05)."""
    from dat_replication_protocol_tpu.obs.loopprof import LoopProfiler

    prof = LoopProfiler(name, tick=0.05)
    prof.attach()
    prof.turn_begin(10.0)
    prof.poll_done(10.001, 1)
    prof.turn_done(10.001 + lag_turn_s, sessions=1)
    return prof


def test_loop_lag_slo_passes_on_caught_up_loop(obs_enabled):
    prof = _tracked_loop("edge-ok", 0.001)  # clean: lag exactly 0
    try:
        view = FleetView([FleetTarget(default_snapshot, name="t0")])
        sample = view.poll()
        assert sample["loops"]["t0:edge-ok"]["lag_s"] == 0.0
        rows = [r for r in evaluate_slo({"max_loop_lag_s": 0.25}, sample)
                if r["check"] == "max_loop_lag_s"]
        assert rows and all(r["status"] == "ok" for r in rows)
    finally:
        prof.detach()


def test_loop_lag_slo_fails_on_loop_behind(obs_enabled):
    prof = _tracked_loop("edge-slow", 0.6)  # 0.55s of lag
    try:
        view = FleetView([FleetTarget(default_snapshot, name="t0")])
        rows = [r for r in
                evaluate_slo({"max_loop_lag_s": 0.25}, view.poll())
                if r["check"] == "max_loop_lag_s"]
        assert rows and rows[0]["status"] == "fail"
        assert rows[0]["subject"] == "t0:edge-slow"
        assert "0.550" in rows[0]["detail"]
    finally:
        prof.detach()


def test_loop_lag_slo_fails_loudly_on_dark_loop(obs_enabled):
    """A loop whose gate is off must FAIL the check, not pass on stale
    zeros — dark telemetry is an answer of 'unknown', and the SLO gate
    treats unknown as breach."""
    from dat_replication_protocol_tpu.obs import metrics

    prof = _tracked_loop("edge-dark", 0.001)
    try:
        view = FleetView([FleetTarget(default_snapshot, name="t0")])
        metrics.OBS.on = False
        sample = view.poll()
        metrics.enable()
        assert sample["loops"]["t0:edge-dark"]["state"] == "dark"
        rows = [r for r in
                evaluate_slo({"max_loop_lag_s": 0.25}, sample)
                if r["check"] == "max_loop_lag_s"]
        assert rows and rows[0]["status"] == "fail"
        assert "dark" in rows[0]["detail"]
    finally:
        metrics.enable()
        prof.detach()


def test_loop_lag_slo_fails_when_no_target_reports_loops(obs_enabled):
    view = FleetView([FleetTarget(default_snapshot, name="t0")])
    rows = [r for r in
            evaluate_slo({"max_loop_lag_s": 0.25}, view.poll())
            if r["check"] == "max_loop_lag_s"]
    assert rows and rows[0]["status"] == "fail"
    assert "no targets report" in rows[0]["detail"]


def test_dashboard_renders_loop_lag_section(obs_enabled):
    prof = _tracked_loop("edge-dash", 0.3)
    try:
        view = FleetView([FleetTarget(default_snapshot, name="t0")])
        screen = render_dashboard(view, view.poll())
        assert "t0:edge-dash" in screen
    finally:
        prof.detach()


# -- SLO gate (the tier-1 live gate) ------------------------------------------


def _converged_two_replica_scenario():
    """The 2-replica in-process scenario the tier-1 gate runs: sender
    journal + receiver decoder, both watermarked on one link, run to
    byte-identical completion."""
    wire = _build_wire(16)
    j = WireJournal()
    j.watermark("gate")
    j.append(wire)
    dec = protocol.decode()
    dec.watermark("gate")
    dec.change(lambda c, done: done())
    dec.blob(lambda b, done: b.collect(lambda _d: done()))
    dec.write(wire)
    dec.end()
    assert dec.finished
    return j, dec


def test_fleet_check_gate_passes_on_converged_fleet(obs_enabled, tmp_path):
    _converged_two_replica_scenario()
    slo = tmp_path / "slo.json"
    slo.write_text(json.dumps({
        "max_lag_bytes": 0, "max_lag_seconds": 0.5,
        "require_converged": True, "max_shed": 0, "max_rejected": 0,
        "recompile_budget": 4, "max_events_dropped": 0,
    }))
    import io

    out = io.StringIO()
    rc = run_fleet_check([default_snapshot], str(slo), polls=2,
                         interval=0.01, out=out)
    assert rc == 0, out.getvalue()
    assert "within SLO" in out.getvalue()
    WATERMARKS.untrack("gate")


def test_fleet_check_gate_fails_on_doctored_lag(obs_enabled, tmp_path):
    wire = _build_wire(16)
    j = WireJournal()
    j.watermark("gate-bad")
    j.append(wire)
    dec = protocol.decode()
    dec.watermark("gate-bad")
    dec.change(lambda c, done: done())
    dec.blob(lambda b, done: b.collect(lambda _d: done()))
    dec.write(wire[: len(wire) // 2])  # stuck mid-wire: real lag
    slo = tmp_path / "slo.json"
    slo.write_text(json.dumps({"require_converged": True}))
    import io

    out = io.StringIO()
    rc = run_fleet_check([default_snapshot], str(slo), polls=1, out=out)
    assert rc == 1
    assert "SLO BREACH" in out.getvalue()
    WATERMARKS.untrack("gate-bad")


@pytest.mark.parametrize("content", [
    "not json at all",
    '["a", "list"]',
    "{}",
    '{"bogus_key": 1}',
    '{"max_lag_bytes": "lots"}',
    '{"require_converged": 1}',
])
def test_fleet_check_malformed_slo_fails_loudly(tmp_path, content):
    slo = tmp_path / "slo.json"
    slo.write_text(content)
    import io

    out = io.StringIO()
    rc = run_fleet_check([lambda: {"watermarks": {"links": {}}}],
                         str(slo), polls=1, out=out)
    assert rc == 1
    assert "FAIL slo" in out.getvalue()
    with pytest.raises((ValueError, json.JSONDecodeError)):
        load_slo(str(slo))


def test_fleet_check_cli_end_to_end(obs_enabled, tmp_path, capsys):
    from dat_replication_protocol_tpu.obs.__main__ import main
    from dat_replication_protocol_tpu.sidecar import snapshot_stats

    _converged_two_replica_scenario()
    target = tmp_path / "replica.jsonl"
    snap = snapshot_stats()
    snap["emit_seq"] = 0
    target.write_text(json.dumps(snap) + "\n")
    slo = tmp_path / "slo.json"
    slo.write_text(json.dumps({"max_lag_bytes": 0}))
    assert main(["fleet", str(target), "--check", str(slo),
                 "--polls", "1"]) == 0
    assert "within SLO" in capsys.readouterr().out
    # snapshot_stats embeds the staged healthz record, so file targets
    # can evaluate require_healthz...
    slo.write_text(json.dumps({"max_lag_seconds": 0.0,
                               "require_healthz": True}))
    assert main(["fleet", str(target), "--check", str(slo),
                 "--polls", "1"]) == 0
    # ...and a snapshot WITHOUT one (a bare/doctored record) must make
    # the gate FAIL, never silently skip the stage
    del snap["healthz"]
    target.write_text(json.dumps(snap) + "\n")
    assert main(["fleet", str(target), "--check", str(slo),
                 "--polls", "1"]) == 1
    WATERMARKS.untrack("gate")


def test_dashboard_renders_one_screen(obs_enabled):
    _converged_two_replica_scenario()
    view = FleetView([FleetTarget(default_snapshot, name="replica-a")])
    sample = view.poll(healthz=True)
    frame = render_dashboard(view, sample)
    assert "replica-a" in frame
    assert "gate" in frame  # the link row
    assert "lag_bytes" in frame
    assert "\x1b[" not in frame  # plain text; the CLI owns the clear
    WATERMARKS.untrack("gate")


# -- N-log timeline (the offline mirror) -------------------------------------


def _frame_line(span: str, seq: int, offset: int, wire_len: int,
                link=None) -> str:
    fields = {"offset": offset, "wire_len": wire_len}
    if link is not None:
        fields["link"] = link
    return json.dumps({"span": span, "seq": seq, "ts": float(seq),
                       "fields": fields}) + "\n"


def _write_log(path, span, frames, link=None):
    path.write_text("".join(
        _frame_line(span, i, off, wl, link)
        for i, (off, wl) in enumerate(frames)))


def test_timeline_three_logs_clean_fanout_merge(tmp_path, capsys):
    from dat_replication_protocol_tpu.obs.__main__ import main

    frames = [(0, 10), (10, 20), (30, 5)]
    s = tmp_path / "sender.jsonl"
    r1 = tmp_path / "r1.jsonl"
    r2 = tmp_path / "r2.jsonl"
    _write_log(s, "encoder.frame", frames)
    _write_log(r1, "decoder.frame", frames)
    _write_log(r2, "decoder.frame", frames)
    rc = main(["timeline", str(s), str(r1), str(r2), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["flags"] == []
    # fan-out shape: ONE emitter serves BOTH dispatch streams
    assert len(out["links"]) == 2
    assert all(ln["emitter"] == "sender.jsonl" for ln in out["links"])
    assert {ln["dispatcher"] for ln in out["links"]} == \
        {"r1.jsonl", "r2.jsonl"}
    assert set(out["peers"]) == {"sender.jsonl", "r1.jsonl", "r2.jsonl"}
    # merged rows keyed on offset, emitter-first at equal offsets
    first = [w for w in out["timeline"] if w["offset"] == 0]
    assert first[0]["role"] == "sender.jsonl"


def test_timeline_three_logs_doctored_gap_flagged(tmp_path, capsys):
    from dat_replication_protocol_tpu.obs.__main__ import main

    frames = [(0, 10), (10, 20), (30, 5)]
    s = tmp_path / "sender.jsonl"
    r1 = tmp_path / "r1.jsonl"
    r2 = tmp_path / "r2.jsonl"
    _write_log(s, "encoder.frame", frames)
    _write_log(r1, "decoder.frame", frames)
    _write_log(r2, "decoder.frame", [(0, 10), (30, 5)])  # dropped a frame
    rc = main(["timeline", str(s), str(r1), str(r2), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    flagged = {f["flag"] for f in out["flags"]}
    assert "gap" in flagged  # r2's own coverage hole
    assert "peer-divergence" in flagged  # vs its paired emitter


def test_timeline_link_labels_beat_coverage_matching(tmp_path, capsys):
    from dat_replication_protocol_tpu.obs.__main__ import main

    # two independent wires with IDENTICAL coverage: only the link
    # label can pair them correctly
    frames = [(0, 10), (10, 10)]
    sa = tmp_path / "sa.jsonl"
    sb = tmp_path / "sb.jsonl"
    ra = tmp_path / "ra.jsonl"
    rb = tmp_path / "rb.jsonl"
    _write_log(sa, "encoder.frame", frames, link="wire-a")
    _write_log(sb, "encoder.frame", frames, link="wire-b")
    _write_log(ra, "decoder.frame", frames, link="wire-a")
    _write_log(rb, "decoder.frame", frames, link="wire-b")
    rc = main(["timeline", str(sa), str(sb), str(ra), str(rb), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    pair = {ln["link"]: (ln["emitter"], ln["dispatcher"])
            for ln in out["links"]}
    assert pair == {"wire-a": ("sa.jsonl", "ra.jsonl"),
                    "wire-b": ("sb.jsonl", "rb.jsonl")}


def test_timeline_two_logs_unchanged(tmp_path, capsys):
    # the exactly-2 path keeps the classic sender/receiver JSON shape
    from dat_replication_protocol_tpu.obs.__main__ import main

    frames = [(0, 10), (10, 20)]
    s = tmp_path / "s.jsonl"
    r = tmp_path / "r.jsonl"
    _write_log(s, "encoder.frame", frames)
    _write_log(r, "decoder.frame", frames)
    rc = main(["timeline", str(s), str(r), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["sender"]["frames"] == 2 and out["receiver"]["frames"] == 2


# -- sidecar integration ------------------------------------------------------


def test_sidecar_obs_http_flag_serves_session_watermarks(obs_enabled):
    """--obs-http end to end: a real sidecar TCP session's receive
    cursors appear on /snapshot while the session runs, and the link
    vanishes once the session ends (bounded cardinality)."""
    import socket

    from dat_replication_protocol_tpu.obs.http import ObsHttpServer
    from dat_replication_protocol_tpu.sidecar import (
        serve_tcp,
        snapshot_stats,
    )

    wire = _build_wire(6)
    srv = ObsHttpServer(0, snapshot_fn=snapshot_stats).start()
    ready = threading.Event()
    port_box: dict = {}

    def _serve():
        serve_tcp("127.0.0.1", 0, max_sessions=1,
                  ready_cb=lambda p: (port_box.update(port=p),
                                      ready.set()),
                  drain_timeout=10)

    t = threading.Thread(target=_serve, daemon=True)
    t.start()
    assert ready.wait(10)
    with socket.create_connection(("127.0.0.1", port_box["port"]),
                                  timeout=10) as conn:
        conn.sendall(wire)
        conn.shutdown(socket.SHUT_WR)
        while conn.recv(4096):
            pass
    t.join(timeout=10)
    snap = json.loads(_get(srv.url + "/snapshot")[1])
    srv.close()
    # the session closed: its link must be GONE from the board
    assert not any(k.startswith("c1:")
                   for k in snap["watermarks"]["links"])


# -- mesh convergence SLO plumbing (ISSUE 19) --------------------------------


from dat_replication_protocol_tpu.obs.fleet import (  # noqa: E402
    MESH_SLO_KEYS,
    _join_mesh,
    mesh_rounds_floor,
)


def _prop_snap(links=None, frontier=None, p99=None, count=0):
    return {"monotonic": 0.0, "links": links or {},
            "frontier": frontier or {},
            "exchange_seconds": {"count": count, "p50": p99, "p99": p99}}


def _link(rnd, *, outcome="progress", div_rec=2, div_b=128, ok_age=0.5):
    return {"role": "initiator", "round": rnd, "outcome": outcome,
            "divergence_records": div_rec, "divergence_bytes": div_b,
            "wire_bytes": 256, "seconds": 0.01, "exchanges": 1,
            "failures": 0, "error": None, "age_s": 0.1,
            "last_success_age_s": ok_age}


def test_join_mesh_freshest_link_wins_and_p99_is_the_max():
    snaps = {
        "t0": {"propagation": _prop_snap(
            links={"r0->r1": _link(2, div_rec=5)},
            frontier={"r0": {"digest": "aa", "records": 3, "round": 2}},
            p99=0.02, count=4)},
        "t1": {"propagation": _prop_snap(
            links={"r0->r1": _link(4, div_rec=1)},
            frontier={"r1": {"digest": "bb", "records": 2, "round": 4}},
            p99=0.08, count=6)},
    }
    mesh = _join_mesh(snaps)
    assert mesh["links"]["r0->r1"]["round"] == 4
    assert mesh["links"]["r0->r1"]["divergence_records"] == 1
    assert mesh["links"]["r0->r1"]["target"] == "t1"
    assert mesh["exchange_p99_s"] == 0.08
    assert mesh["exchange_count"] == 10
    # frontiers differ: the pair is NOT converged, watermark stands
    pair = mesh["pairs"]["r0<->r1"]
    assert not pair["converged"]
    assert pair["divergence_records"] == 1


def test_join_mesh_frontier_equality_overrides_stale_watermark():
    """A link watermark is the diff at the pair's LAST exchange; once
    both frontiers are byte-identical the pair's divergence is exactly
    0 whatever a stale watermark says (the smoke-test lesson: a link
    that last exchanged at round 1 with diff 4 and never re-exchanged
    must not read as diverged after the mesh converged)."""
    snaps = {"t0": {"propagation": _prop_snap(
        links={"r0->r1": _link(1, div_rec=4, div_b=400)},
        frontier={"r0": {"digest": "cc", "records": 5, "round": 3},
                  "r1": {"digest": "cc", "records": 5, "round": 3}})}}
    pair = _join_mesh(snaps)["pairs"]["r0<->r1"]
    assert pair["converged"]
    assert pair["divergence_records"] == 0
    assert pair["divergence_bytes"] == 0


def test_join_mesh_empty_when_nothing_reports():
    assert _join_mesh({"t0": {"gossip": {}}, "t1": None}) == {}


@pytest.mark.parametrize("key", sorted(MESH_SLO_KEYS))
def test_mesh_slo_keys_must_be_numeric(tmp_path, key):
    path = tmp_path / "slo.json"
    path.write_text(json.dumps({"gossip": {key: "fast"}}))
    with pytest.raises(ValueError, match="must be a number"):
        load_slo(str(path))
    path.write_text(json.dumps({"gossip": {key: 10}}))
    assert load_slo(str(path))["gossip"][key] == 10


def test_mesh_slo_dark_plane_fails_loudly():
    slo = {"gossip": {"max_divergence_bytes": 0}}
    sample = {"links": {}, "gossip": {"t0": {
        "replica": "r0", "round": 3, "rounds_behind": 0, "records": 1,
        "digest": "aa", "quarantined": [], "quarantine": {},
        "suspicion": {}}}, "mesh": {}}
    rows = [r for r in evaluate_slo(slo, sample)
            if r["check"] == "gossip.mesh"]
    assert rows and rows[0]["status"] == "fail"
    assert "no targets report propagation records" in rows[0]["detail"]


def test_mesh_slo_unreachable_convergence_bound_is_a_misconfig():
    """A max_convergence_rounds below the epidemic floor fails as an
    SLO bug, not as a mesh failure — an unreachable gate is a
    misconfiguration, never a standard."""
    assert mesh_rounds_floor(2) == 13
    assert mesh_rounds_floor(4) == 16
    assert mesh_rounds_floor(64) == 28
    mesh = {"frontier": {f"r{i}": {"digest": "aa", "round": 2}
                         for i in range(4)},
            "links": {}, "pairs": {}, "exchange_p99_s": None,
            "exchange_count": 0}
    slo = {"gossip": {"max_convergence_rounds": 15}}
    rows = evaluate_slo(slo, {"links": {}, "gossip": {}, "mesh": mesh})
    (row,) = [r for r in rows
              if r["check"] == "gossip.max_convergence_rounds"]
    assert row["status"] == "fail"
    assert "unreachable SLO" in row["detail"]
    # at the floor it evaluates for real — converged at round 2 passes
    slo = {"gossip": {"max_convergence_rounds": 16}}
    rows = evaluate_slo(slo, {"links": {}, "gossip": {}, "mesh": mesh})
    (row,) = [r for r in rows
              if r["check"] == "gossip.max_convergence_rounds"]
    assert row["status"] == "ok"
    assert "converged at round 2" in row["detail"]


def test_mesh_slo_silently_dead_link_fails_age_check():
    mesh = {"frontier": {"r0": {"digest": "aa", "round": 1},
                         "r1": {"digest": "bb", "round": 1}},
            "links": {"r0->r1": dict(_link(1), last_success_age_s=None)},
            "pairs": {"r0<->r1": {"round": 1, "converged": False,
                                  "divergence_records": 2,
                                  "divergence_bytes": 128,
                                  "last_success_age_s": None,
                                  "outcome": "transport"}},
            "exchange_p99_s": 0.01, "exchange_count": 1}
    slo = {"gossip": {"max_exchange_age_s": 60}}
    rows = evaluate_slo(slo, {"links": {}, "gossip": {}, "mesh": mesh})
    (row,) = [r for r in rows
              if r["check"] == "gossip.max_exchange_age_s"]
    assert row["status"] == "fail"
    assert "silently-dead link" in row["detail"]


def test_dashboard_renders_the_mesh_matrix():
    sample = {
        "ts": 0.0, "targets": {}, "links": {}, "dropped_lines": {},
        "gossip": {"t0": {"replica": "r0", "round": 3,
                          "rounds_behind": 0, "records": 4,
                          "digest": "aa" * 16, "quarantined": ["rX"],
                          "quarantine": {"rX": {"arm": "wrong-symbol",
                                                "frame": 2,
                                                "offset": 17}},
                          "suspicion": {}}},
        "mesh": {"links": {}, "frontier": {},
                 "pairs": {"r0<->r1": {"round": 3, "converged": True,
                                       "divergence_records": 0,
                                       "divergence_bytes": 0,
                                       "last_success_age_s": 0.25,
                                       "outcome": "converged"}},
                 "exchange_p99_s": 0.0123, "exchange_count": 42},
    }
    view = FleetView([FleetTarget(lambda: {}, name="t0")])
    frame = render_dashboard(view, sample)
    assert "r0<->r1" in frame
    assert "converged" in frame
    assert "exchange p99 0.0123s over 42 exchange(s)" in frame
    assert "quarantine r0: rX arm=wrong-symbol frame=2 offset=17" \
        in frame
