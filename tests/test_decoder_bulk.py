"""The decoder's native-indexed bulk path vs the streaming scanner.

``Decoder._start_indexed``/``_run_indexed`` must be observably identical
to the per-byte scan path: same callbacks, same ordering, same errors,
same flow control — only faster.  These tests force the bulk path
(>= 4 KiB writes at a frame boundary) and the streaming path over the
same wires and compare, including the cases the round-3 review flagged:
async acks (cursor resume, not re-indexing), corrupt records mid-bulk,
invalid UTF-8, zero-length-adjacent blobs, and u64-varint truncation
parity between the native columnar decoder and the Python one.
"""

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.runtime import native
from dat_replication_protocol_tpu.wire.change_codec import encode_change
from dat_replication_protocol_tpu.wire.framing import (
    TYPE_BLOB,
    TYPE_CHANGE,
    frame,
)
from dat_replication_protocol_tpu.wire.varint import encode_uvarint

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


@pytest.fixture(params=["c-dispatch", "python-dispatch"], autouse=True)
def both_dispatch_paths(request, monkeypatch):
    """Every test in this module runs against BOTH bulk dispatch
    implementations (the dat_fastpath C loop and the pure-Python
    fallback): an image with a toolchain would otherwise never execute
    the fallback, and one without would never execute the C loop — a
    divergence between them could ship green either way."""
    if request.param == "python-dispatch":
        monkeypatch.setenv("DAT_FASTPATH_DISABLE", "1")


def _wire(n=400, blob_every=7):
    parts = []
    for i in range(n):
        parts.append(frame(TYPE_CHANGE, encode_change({
            "key": f"key-{i}", "change": i, "from": i, "to": i + 1,
            "value": b"v" * (i % 90), "subset": "s" if i % 3 else None,
        })))
        if i % blob_every == 0:
            parts.append(frame(TYPE_BLOB, bytes([i & 255]) * (i % 300)))
    return b"".join(parts)


def _drive(wire, chunk_size):
    dec = protocol.decode()
    events = []
    dec.change(lambda ch, done: (events.append(("c", ch)), done()))

    def on_blob(blob, done):
        parts = []
        blob.on_data(parts.append)
        blob.on_end(lambda: (events.append(("b", b"".join(parts))), done()))

    dec.blob(on_blob)
    for off in range(0, len(wire), chunk_size):
        dec.write(wire[off : off + chunk_size])
    dec.end()
    assert dec.finished
    return events


def test_bulk_path_matches_streaming_scanner():
    wire = _wire()
    bulk = _drive(wire, 1 << 16)  # >= _NATIVE_MIN: indexed
    slow = _drive(wire, 97)  # tiny writes: per-byte scanner
    assert bulk == slow
    assert len(bulk) > 400


def test_async_acks_resume_from_cursor():
    # every ack deferred: the parked cursor must resume without loss,
    # duplication, or reordering
    wire = _wire(n=300, blob_every=5)
    dec = protocol.decode()
    events = []
    pending = []
    dec.change(lambda ch, done: (events.append(("c", ch.key)),
                                 pending.append(done)))
    dec.blob(lambda blob, done: blob.collect(
        lambda d: (events.append(("b", len(d))), done())))
    writes = [dec.write(wire)]
    dec.end()
    while pending:
        pending.pop(0)()
    assert dec.finished
    keys = [e[1] for e in events if e[0] == "c"]
    assert keys == [f"key-{i}" for i in range(300)]
    assert writes == [False]  # stalled on the first withheld ack


def test_corrupt_record_mid_bulk_delivers_prefix_then_destroys():
    frames = [frame(TYPE_CHANGE, encode_change({
        "key": f"z{i}", "change": i, "from": 0, "to": 1})) for i in range(60)]
    blob = bytearray(b"".join(frames))
    # corrupt frame 40's payload: 0x07 is an invalid proto wire type
    off40 = sum(len(f) for f in frames[:40])
    blob[off40 + 2] = 0x07
    dec = protocol.decode()
    seen, errs = [], []
    dec.change(lambda ch, done: (seen.append(ch.key), done()))
    dec.on_error(errs.append)
    dec.write(bytes(blob))
    assert dec.destroyed and errs
    assert seen == [f"z{i}" for i in range(40)]


def test_invalid_utf8_key_destroys_with_protocol_error():
    frames = [frame(TYPE_CHANGE, encode_change({
        "key": f"u{i}", "change": i, "from": 0, "to": 1})) for i in range(40)]
    # hand-build a record whose key bytes are invalid UTF-8
    bad_payload = bytes([0x12, 0x02, 0xFF, 0xFE,  # key = b"\xff\xfe"
                         0x18, 0x01, 0x20, 0x00, 0x28, 0x01])
    frames.insert(20, frame(TYPE_CHANGE, bad_payload))
    dec = protocol.decode()
    seen, errs = [], []
    dec.change(lambda ch, done: (seen.append(ch.key), done()))
    dec.on_error(errs.append)
    dec.write(b"".join(frames))
    assert dec.destroyed
    assert errs and isinstance(errs[0], protocol.ProtocolError)
    assert seen == [f"u{i}" for i in range(20)]


def test_u64_varint_truncates_identically_on_both_paths():
    # a foreign encoder may emit >32-bit varints for uint32 fields;
    # proto2 semantics truncate.  Build the payload by hand.
    big = (1 << 32) + 5
    payload = (bytes([0x12, 0x01]) + b"k"
               + bytes([0x18]) + encode_uvarint(big)
               + bytes([0x20, 0x00, 0x28, 0x01]))
    frames = [frame(TYPE_CHANGE, payload)] * 20
    wire = b"".join(frames)

    def decode_with(chunk):
        dec = protocol.decode()
        out = []
        dec.change(lambda ch, done: (out.append(ch.change), done()))
        for off in range(0, len(wire), chunk):
            dec.write(wire[off : off + chunk])
        dec.end()
        return out

    bulk = decode_with(len(wire))
    slow = decode_with(7)
    assert bulk == slow == [5] * 20


def test_bulk_then_partial_blob_tail():
    # a complete run of frames followed by a blob frame whose payload is
    # still arriving: indexed dispatch for the run, streaming for the tail
    head = _wire(n=64, blob_every=9)
    blob_frame = frame(TYPE_BLOB, b"Q" * 100_000)
    dec = protocol.decode()
    got = {"c": 0, "bytes": 0, "ended": 0}
    dec.change(lambda ch, done: (got.__setitem__("c", got["c"] + 1), done()))

    def on_blob(blob, done):
        blob.on_data(lambda ch: got.__setitem__(
            "bytes", got["bytes"] + len(ch)))
        blob.on_end(lambda: (got.__setitem__("ended", got["ended"] + 1),
                             done()))

    dec.blob(on_blob)
    wire = head + blob_frame
    split = len(head) + 5000  # mid-payload of the trailing blob
    dec.write(wire[:split])
    dec.write(wire[split:])
    dec.end()
    assert dec.finished
    assert got["c"] == 64
    assert got["bytes"] == sum((i % 300) for i in range(64) if i % 9 == 0) + 100_000


def test_corrupt_header_mid_bulk_delivers_prefix_then_destroys():
    # a malformed frame HEADER (not payload): delivery-before-error must
    # not depend on write chunking (round-3 review finding)
    frames = [frame(TYPE_CHANGE, encode_change({
        "key": f"h{i}", "change": i, "from": 0, "to": 1})) for i in range(40)]
    wire = b"".join(frames) + bytes([0x80] * 10 + [0x01])  # overlong varint

    def drive(chunk):
        dec = protocol.decode()
        seen, errs = [], []
        dec.change(lambda ch, done: (seen.append(ch.key), done()))
        dec.on_error(errs.append)
        for off in range(0, len(wire), chunk):
            if dec.destroyed:
                break
            dec.write(wire[off : off + chunk])
        return seen, errs, dec.destroyed

    bulk = drive(len(wire))
    slow = drive(13)
    assert bulk[2] and slow[2]
    assert bulk[0] == slow[0] == [f"h{i}" for i in range(40)]
    assert bulk[1] and slow[1]


def test_blob_pause_in_handler_defers_payload_in_bulk():
    # a handler that pause()s synchronously must not receive the payload
    # until resume — identical to the streaming path (review finding)
    head = b"".join(frame(TYPE_CHANGE, encode_change({
        "key": f"p{i}", "change": i, "from": 0, "to": 1})) for i in range(20))
    wire = head + frame(TYPE_BLOB, b"Z" * 5000) + frame(
        TYPE_CHANGE, encode_change({"key": "after", "change": 1, "from": 0,
                                    "to": 1}))
    dec = protocol.decode()
    got = {"chunks": [], "keys": []}
    holder = {}
    dec.change(lambda ch, done: (got["keys"].append(ch.key), done()))

    def on_blob(blob, done):
        blob.pause()
        holder["blob"] = blob
        blob.on_data(got["chunks"].append)
        blob.on_end(done)

    dec.blob(on_blob)
    dec.write(wire)
    assert got["chunks"] == [], "payload delivered despite pause()"
    assert got["keys"] == [f"p{i}" for i in range(20)]
    holder["blob"].resume()
    dec.end()
    assert dec.finished
    assert b"".join(got["chunks"]) == b"Z" * 5000
    assert got["keys"][-1] == "after"


def test_fuzz_random_chunking_equivalence():
    # any split of the same wire must produce identical events
    import random as pyrandom

    rng = pyrandom.Random(42)
    wire = _wire(n=120, blob_every=4)
    ref = _drive(wire, len(wire))
    for trial in range(8):
        dec = protocol.decode()
        events = []
        dec.change(lambda ch, done: (events.append(("c", ch)), done()))
        dec.blob(lambda blob, done: blob.collect(
            lambda d: (events.append(("b", d)), done())))
        off = 0
        while off < len(wire):
            step = rng.choice([1, 3, 17, 255, 4096, 9999])
            dec.write(wire[off : off + step])
            off += step
        dec.end()
        assert dec.finished, trial
        assert events == ref, trial


def test_fuzz_hostile_bytes_never_hang():
    # random garbage: the decoder must either destroy with ProtocolError
    # or consume cleanly (if it happens to parse) — never crash or hang
    import random as pyrandom

    rng = pyrandom.Random(7)
    for trial in range(20):
        blob = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 9000)))
        dec = protocol.decode()
        errs = []
        dec.on_error(errs.append)
        try:
            dec.write(blob)
        except Exception as e:  # noqa: BLE001
            raise AssertionError(f"trial {trial}: decoder raised {e!r}")
        if dec.destroyed:
            assert errs, trial


def test_double_ack_on_bulk_path_is_noop():
    """The fast-path done is one-shot: a second (or third) call must not
    double-decrement pending or corrupt later frames' flow control."""
    wire = _wire(n=120, blob_every=1 << 30)
    dec = protocol.decode()
    seen, dones = [], []
    dec.change(lambda ch, done: (seen.append(ch.key), dones.append(done),
                                 done(), done()))  # sync ack, twice
    dec.write(wire)
    dec.end()
    assert dec.finished
    assert seen == [f"key-{i}" for i in range(120)]
    for d in dones:  # and a long-stale third call after finish
        d()
    assert dec.finished and not dec.destroyed


def test_cross_thread_ack_race_never_loses_or_doublecounts():
    """Hammer the handler-returns vs done()-from-another-thread window:
    every change is acked from a worker thread immediately; the session
    must always complete with every key delivered exactly once."""
    import threading

    wire = _wire(n=200, blob_every=1 << 30)
    for _ in range(20):
        dec = protocol.decode()
        seen = []
        threads = []

        def on_change(ch, done):
            seen.append(ch.key)
            t = threading.Thread(target=done)
            t.start()
            threads.append(t)

        dec.change(on_change)
        done_box = []
        dec.write(wire)
        dec.end(lambda: done_box.append(1))
        for t in threads:
            t.join(timeout=5)
        deadline = 100
        while not dec.finished and deadline:
            deadline -= 1
            import time
            time.sleep(0.01)
        assert dec.finished, "session never finished: an ack was lost"
        assert seen == [f"key-{i}" for i in range(200)]
        assert done_box == [1]


def test_changes_counter_increments_before_each_callback():
    wire = _wire(n=50, blob_every=1 << 30)
    dec = protocol.decode()
    observed = []
    dec.change(lambda ch, done: (observed.append(dec.changes), done()))
    dec.write(wire)
    dec.end()
    assert observed == list(range(1, 51))


def test_handler_valueerror_propagates_not_protocolerror():
    """A handler bug that raises ValueError must surface as that
    ValueError to write()'s caller — on BOTH dispatch paths — never be
    misread as a wire error that destroys the session (round-5 review:
    the C loop once wrapped handler calls in the decode-error handler)."""
    wire = _wire(n=40, blob_every=1 << 30)
    dec = protocol.decode()
    seen = []

    def handler(ch, done):
        seen.append(ch.key)
        if len(seen) == 10:
            raise ValueError("bad app state")
        done()

    dec.change(handler)
    errs = []
    dec.on_error(errs.append)
    with pytest.raises(ValueError, match="bad app state"):
        dec.write(wire)
    assert not dec.destroyed  # the decoder was not torn down as a
    assert errs == []         # protocol error; the app owns its bug
    assert seen == [f"key-{i}" for i in range(10)]


def _drive_with_raising_handler(dec, wire, boom):
    """Feed ``wire``, with the change handler raising ValueError after
    acking any change whose counter is in ``boom``; each raise is caught
    and dispatch resumed with an empty write.  Returns (seen, raises):
    the ordered (key, change) pairs delivered and the raise count."""
    seen = []

    def handler(ch, done):
        seen.append((ch.key, ch.change))
        done()
        if ch.change in boom:
            raise ValueError(f"app bug at change {ch.change}")

    dec.change(handler)
    raises = 0
    data = wire
    while True:
        try:
            dec.write(data)
            break
        except ValueError:
            raises += 1
            data = b""  # resume the parked bulk cursor
    dec.end()
    return seen, raises


def test_handler_raise_then_resume_keeps_rows_paired():
    """The round-5 high finding, as a regression test: a handler raise
    mid-bulk must advance BOTH cursor halves (frame index f and columnar
    row) atomically, so that catching the exception and resuming
    dispatch re-enters at the next frame with payloads still paired to
    their own rows.  Pre-fix, the pure-Python fast loop's finally wrote
    back st["row"] but not st["f"]: on resume, frames re-dispatched
    from the stale f against advanced rows — silently wrong Change
    records (this exact key/change pairing assertion), duplicate
    deliveries, then IndexError."""
    n = 200
    wire = _wire(n=n, blob_every=1 << 30)
    dec = protocol.decode()
    seen, raises = _drive_with_raising_handler(dec, wire, boom={17, 95, 160})
    assert dec.finished and not dec.destroyed
    assert raises == 3
    assert seen == [(f"key-{i}", i) for i in range(n)]


def test_handler_raise_then_resume_general_indexed_loop():
    """Same invariant on the GENERAL indexed loop (the non-fast branch
    a _deliver_change subclass rides): row/f advance before the handler
    can raise and persist together in the outer finally.  Pre-fix this
    path advanced st["row"] immediately but st["f"] only at loop exit
    — a raise-then-resume re-delivered frames against later rows."""
    from dat_replication_protocol_tpu.session.decoder import Decoder

    class SubclassedDecoder(Decoder):
        # any override disables the fast change loop (the gate reads
        # cls.__dict__), forcing the general indexed dispatch
        def _deliver_change(self, change, payload):
            super()._deliver_change(change, payload)

    n = 120
    wire = _wire(n=n, blob_every=1 << 30)
    dec = SubclassedDecoder()
    seen, raises = _drive_with_raising_handler(dec, wire, boom={3, 64, 65})
    assert dec.finished and not dec.destroyed
    assert raises == 3
    assert seen == [(f"key-{i}", i) for i in range(n)]


def test_blob_handler_raise_then_resume_delivers_payload_once():
    """The blob half of the raise-then-resume invariant: a blob on_data
    callback that raises mid-bulk must not see the same chunk again
    after the app catches and resumes — delivery consumes the frame.
    Pre-fix, the bulk loop advanced f and cleared blob_open only AFTER
    _blob_data, so a resume re-ran the delivery: duplicate blob bytes
    (and, on a digest decoder, a corrupt blob digest)."""
    head = b"".join(frame(TYPE_CHANGE, encode_change({
        "key": f"k{i}", "change": i, "from": 0, "to": 1,
        "value": b"v" * 80})) for i in range(30))
    wire = head + frame(TYPE_BLOB, b"B" * 500) + frame(
        TYPE_CHANGE, encode_change({"key": "after", "change": 1,
                                    "from": 0, "to": 1}))
    dec = protocol.decode()
    keys, chunks, boom = [], [], [True]
    dec.change(lambda ch, done: (keys.append(ch.key), done()))

    def on_blob(blob, done):
        def on_data(chunk):
            chunks.append(bytes(chunk))
            if boom:
                boom.clear()
                raise ValueError("blob handler bug")

        blob.on_data(on_data)
        blob.on_end(done)

    dec.blob(on_blob)
    with pytest.raises(ValueError, match="blob handler bug"):
        dec.write(wire)
    dec.write(b"")  # resume the parked cursor
    dec.end()
    assert dec.finished and not dec.destroyed
    assert b"".join(chunks) == b"B" * 500, "blob payload re-delivered"
    assert keys == [f"k{i}" for i in range(30)] + ["after"]


@pytest.mark.parametrize("n_head", [30, 2])  # bulk path / streaming path
def test_blob_raise_on_final_chunk_still_ends_blob(n_head):
    """A reader on_data raise on the blob's FINAL chunk must not skip
    _end_blob: pre-fix, _blob_data raised through the missing==0 check,
    leaving _state=TYPE_BLOB and _current_blob dangling — with the blob
    as the last frame, on_end never fired and end() destroyed a fully
    delivered stream with 'stream ended mid-frame'.  (The earlier
    raise-then-resume test masked this: its trailing change frame reset
    _state on the next dispatch.)"""
    head = b"".join(frame(TYPE_CHANGE, encode_change({
        "key": f"k{i}", "change": i, "from": 0, "to": 1,
        "value": b"v" * 80})) for i in range(n_head))
    wire = head + frame(TYPE_BLOB, b"B" * 500)  # blob LAST — no healer
    dec = protocol.decode()
    chunks, boom, ended = [], [True], []
    dec.change(lambda ch, done: done())

    def on_blob(blob, done):
        def on_data(chunk):
            chunks.append(bytes(chunk))
            if boom:
                boom.clear()
                raise ValueError("blob handler bug")

        blob.on_data(on_data)
        blob.on_end(lambda: (ended.append(True), done()))

    dec.blob(on_blob)
    with pytest.raises(ValueError, match="blob handler bug"):
        dec.write(wire)
    dec.write(b"")  # resume
    dec.end()
    assert ended, "on_end never fired for the fully delivered blob"
    assert dec.finished and not dec.destroyed
    assert b"".join(chunks) == b"B" * 500


@pytest.mark.parametrize("n_head", [30, 2])  # bulk path / streaming path
def test_zero_length_blob_handler_raise_still_ends_blob(n_head):
    """Zero-length twin of the final-chunk case: with no payload bytes
    to route through _blob_data, the only end site is
    _open_blob_if_ready's missing==0 check — which a handler raise used
    to skip, on BOTH dispatch paths, leaving the reader dangling and
    end() destroying the stream."""
    head = b"".join(frame(TYPE_CHANGE, encode_change({
        "key": f"k{i}", "change": i, "from": 0, "to": 1,
        "value": b"v" * 80})) for i in range(n_head))
    wire = head + frame(TYPE_BLOB, b"")  # zero-length blob LAST
    dec = protocol.decode()
    boom, ended = [True], []
    dec.change(lambda ch, done: done())

    def on_blob(blob, done):
        blob.on_end(lambda: (ended.append(True), done()))
        if boom:
            boom.clear()
            raise ValueError("blob handler bug")

    dec.blob(on_blob)
    with pytest.raises(ValueError, match="blob handler bug"):
        dec.write(wire)
    dec.write(b"")  # resume
    dec.end()
    assert ended, "zero-length blob never ended after the handler raise"
    assert dec.finished and not dec.destroyed


def test_randomized_ack_schedule_soak():
    """Bounded version of the round-5 ack soak (7-min run: 3,756 sessions
    clean): randomized sync / cross-thread / double / late acks across
    sessions; a lost ack hangs the session and trips the deadline."""
    import random
    import threading
    import time

    for seed in range(6):
        rng = random.Random(seed)
        wire = _wire(n=120, blob_every=11)
        dec = protocol.decode()
        seen = []
        threads = []
        late = []

        def on_change(ch, done):
            seen.append(ch.key)
            mode = rng.random()
            if mode < 0.4:
                done()
                if rng.random() < 0.2:
                    done()
            elif mode < 0.85:
                t = threading.Thread(target=lambda d=done: (d(), d()))
                t.start()
                threads.append(t)
            else:
                late.append(done)

        dec.change(on_change)
        dec.blob(lambda b, done: b.collect(lambda _d: done()))
        for off in range(0, len(wire), 4096):
            deadline = time.time() + 15
            while not dec.writable() and not dec.finished and not dec.destroyed:
                if late:
                    late.pop(0)()
                assert time.time() < deadline, f"stalled, seed {seed}"
                time.sleep(0.0005)
            dec.write(wire[off:off + 4096])
        dec.end()
        deadline = time.time() + 15
        while not dec.finished:
            if late:
                late.pop(0)()
            assert time.time() < deadline, f"finalize hang, seed {seed}"
            time.sleep(0.0005)
        for t in threads:
            t.join(timeout=5)
        assert seen == [f"key-{i}" for i in range(120)], f"seed {seed}"


def test_streaming_raise_then_resume_preserves_chunk_tail():
    """A handler raise mid-chunk on the STREAMING path must requeue the
    chunk's unparsed remainder: pre-fix, _consume popped the chunk and
    the delivery site's `rest` local died with the exception — every
    frame after the raising one in the same write() was silently
    dropped while the session still reported finished=True (the bulk
    path preserves its tail in the parked cursor; this is the
    streaming analogue)."""
    def mkch(k):
        return frame(TYPE_CHANGE, encode_change(
            {"key": k, "change": 1, "from": 0, "to": 1}))

    # blob reader raise: trailing change in the same sub-bulk chunk
    wire = mkch("before") + frame(TYPE_BLOB, b"B" * 50) + mkch("after")
    assert len(wire) < 2048, "must ride the streaming scanner"
    dec = protocol.decode()
    keys, chunks, boom, ended = [], [], [True], []
    dec.change(lambda ch, done: (keys.append(ch.key), done()))

    def on_blob(blob, done):
        def on_data(c):
            chunks.append(bytes(c))
            if boom:
                boom.clear()
                raise ValueError("reader bug")

        blob.on_data(on_data)
        blob.on_end(lambda: (ended.append(True), done()))

    dec.blob(on_blob)
    with pytest.raises(ValueError, match="reader bug"):
        dec.write(wire)
    dec.write(b"")  # resume
    dec.end()
    assert keys == ["before", "after"], f"tail frame lost: {keys}"
    assert b"".join(chunks) == b"B" * 50 and ended
    assert dec.finished and not dec.destroyed

    # change handler raise (ack-then-raise): later frames survive
    dec = protocol.decode()
    keys, boom = [], [True]

    def handler(ch, done):
        keys.append(ch.key)
        done()
        if boom:
            boom.clear()
            raise ValueError("app bug")

    dec.change(handler)
    with pytest.raises(ValueError, match="app bug"):
        dec.write(mkch("a") + mkch("b") + mkch("c"))
    dec.write(b"")
    dec.end()
    assert keys == ["a", "b", "c"], f"tail frames lost: {keys}"
    assert dec.finished and not dec.destroyed

    # blob OPEN raise (handler itself raises; payload + tail follow)
    dec = protocol.decode()
    keys, got, boom, ended = [], [], [True], []
    dec.change(lambda ch, done: (keys.append(ch.key), done()))

    def on_blob2(blob, done):
        blob.on_data(lambda c: got.append(bytes(c)))
        blob.on_end(lambda: (ended.append(True), done()))
        if boom:
            boom.clear()
            raise ValueError("open bug")

    dec.blob(on_blob2)
    with pytest.raises(ValueError, match="open bug"):
        dec.write(frame(TYPE_BLOB, b"PAY") + mkch("tail"))
    dec.write(b"")
    dec.end()
    assert b"".join(got) == b"PAY" and keys == ["tail"] and ended
    assert dec.finished and not dec.destroyed
