"""Pallas BLAKE2b kernel vs hashlib, via the interpreter on CPU.

The real Mosaic compile path runs on TPU (exercised by bench.py and the
driver); these tests check the kernel's logic — layout plumbing, state
chaining across blocks, variable-length masks, batch padding — with
``interpret=True`` on tiny shapes.
"""

import hashlib

import jax.numpy as jnp
import pytest

from dat_replication_protocol_tpu.ops.blake2b import (
    digests_to_bytes,
    pack_payloads,
)
from dat_replication_protocol_tpu.ops.blake2b_pallas import (
    blake2b_packed_pallas,
)


def _run(payloads, nblocks=None):
    mh, ml, lengths = pack_payloads(payloads, nblocks=nblocks)
    hh, hl = blake2b_packed_pallas(
        jnp.asarray(mh), jnp.asarray(ml), jnp.asarray(lengths), interpret=True
    )
    return digests_to_bytes(hh, hl)


def test_variable_lengths_and_padding_match_hashlib():
    # exercises: empty payload, sub-block, exact-block, multi-block items;
    # batch of 5 padded up to the 1024-item kernel tile
    payloads = [b"", b"a" * 7, b"b" * 128, b"c" * 129, bytes(range(256))]
    assert _run(payloads, nblocks=4) == [
        hashlib.blake2b(p, digest_size=32).digest() for p in payloads
    ]


def test_multiblock_chaining():
    payloads = [b"\x5a" * 500, b"\xa5" * 512]
    assert _run(payloads) == [
        hashlib.blake2b(p, digest_size=32).digest() for p in payloads
    ]


@pytest.mark.slow
def test_vmem_state_variant_matches_hashlib():
    # the register-pressure experiment: working-vector lanes in VMEM
    # scratch, per-G load/store.  Tiny shapes: this variant has no
    # scanned form, so interpret compiles the unrolled chain (~30 s of
    # pure compile — slow-marked; the vmem_state COMPOSITIONS stay
    # tier-1 in the state_loads/bps/g_interleave parity tests below)
    from dat_replication_protocol_tpu.ops.blake2b_pallas import (
        blake2b_native,
        from_native,
        to_native,
    )

    payloads = [b"", b"x" * 7, b"y" * 128, b"z" * 200]
    mh, ml, lengths = pack_payloads(payloads, nblocks=2)
    mh_n, ml_n, len_n, B = to_native(
        jnp.asarray(mh), jnp.asarray(ml), jnp.asarray(lengths)
    )
    hh, hl = blake2b_native(mh_n, ml_n, len_n, interpret=True,
                            vmem_state=True)
    assert digests_to_bytes(*from_native(hh, hl, B)) == [
        hashlib.blake2b(p, digest_size=32).digest() for p in payloads
    ]


@pytest.mark.slow
def test_state_loads_variants_byte_exact():
    """The lazy chaining-state view (state_loads) must be byte-exact in
    every composition with msg_loads/vmem_state (mixed lengths so the
    active/final masks take both values).

    slow-marked (tier-1 runtime audit, ISSUE 12): ~30 s of interpret
    COMPILE for a non-default experiment variant no production route
    sets — the default-path parity stays tier-1 in the fast tests, the
    variant parity runs in the slow tier and on-device via
    _when_tpu_returns.sh."""
    import hashlib

    import jax.numpy as jnp
    import numpy as np

    from dat_replication_protocol_tpu.ops.blake2b import (
        digests_to_bytes,
        pack_payloads,
    )
    from dat_replication_protocol_tpu.ops.blake2b_pallas import (
        blake2b_native,
        from_native,
        to_native,
    )

    rng = np.random.default_rng(4)
    payloads = [rng.integers(0, 256, int(n), dtype=np.uint8).tobytes()
                for n in rng.integers(0, 513, 1024)]
    mh, ml, lens = pack_payloads(payloads, nblocks=4)
    mh_n, ml_n, len_n, B = to_native(
        jnp.asarray(mh), jnp.asarray(ml), jnp.asarray(lens)
    )
    # only the vmem_state composition here: its per-G ref loads/stores
    # break the unrolled graph into pieces the CPU interpreter compiles
    # in ~1 min, while the pure-value unrolled graph that state_loads
    # alone produces compiles pathologically (>20 min measured).  The
    # {vmem_state: False, state_loads: True} composition is covered on
    # the real chip: _when_tpu_returns.sh cross-checks it against the
    # baseline with mixed lengths, and bench.py's calibration refuses
    # any variant whose digests differ from the baseline's.
    kw = {"vmem_state": True, "state_loads": True}
    hh, hl = blake2b_native(mh_n, ml_n, len_n, interpret=True,
                            msg_loads=True, **kw)
    digs = digests_to_bytes(*from_native(hh, hl, B))
    for i in (0, 1, 511, 1023):
        exp = hashlib.blake2b(payloads[i], digest_size=32).digest()
        assert digs[i] == exp, (kw, i)


@pytest.mark.slow
def test_blocks_per_step_byte_exact():
    """Multi-block grid steps (chaining state in registers between
    sub-blocks) must match hashlib with mixed lengths, so every item
    finishes at a different sub-block position within a step.

    slow-marked (tier-1 runtime audit, ISSUE 12): ~55 s of interpret
    COMPILE for the bps experiment flag no production route sets (the
    real bps A/B runs on-device via _bps_experiment.py); shrinking the
    batch does not help — the cost is the unroll, not the data."""
    import hashlib

    import jax.numpy as jnp
    import numpy as np

    from dat_replication_protocol_tpu.ops.blake2b import (
        digests_to_bytes,
        pack_payloads,
    )
    from dat_replication_protocol_tpu.ops.blake2b_pallas import (
        blake2b_native,
        from_native,
        to_native,
    )

    rng = np.random.default_rng(11)
    payloads = [rng.integers(0, 256, int(n), dtype=np.uint8).tobytes()
                for n in rng.integers(0, 513, 1024)]
    mh, ml, lens = pack_payloads(payloads, nblocks=4)
    mh_n, ml_n, len_n, B = to_native(
        jnp.asarray(mh), jnp.asarray(ml), jnp.asarray(lens)
    )
    # vmem_state composition for the same interpret-compile-time reason
    # as above; bps=2 only — the interpret compile cost scales with the
    # blocks-per-step unroll, and bps=4 (whole grid in one step) is
    # cross-checked against the baseline on the real chip with mixed
    # lengths by _bps_experiment.py
    hh, hl = blake2b_native(mh_n, ml_n, len_n, interpret=True,
                            msg_loads=True, vmem_state=True,
                            blocks_per_step=2)
    digs = digests_to_bytes(*from_native(hh, hl, B))
    for i in (0, 1, 511, 1023):
        exp = hashlib.blake2b(payloads[i], digest_size=32).digest()
        assert digs[i] == exp, i


def test_g_interleave_byte_exact():
    """The 4-way lockstep G-stage emission must be byte-exact (it is
    pure reordering of independent ops; a lane-indexing slip in
    _g_stage4 would corrupt digests).  interpret forces the unrolled
    rounds for this flag, so the interleaved path really traces."""
    import hashlib

    import jax.numpy as jnp

    from dat_replication_protocol_tpu.ops.blake2b import (
        digests_to_bytes,
        pack_payloads,
    )
    from dat_replication_protocol_tpu.ops.blake2b_pallas import (
        blake2b_native,
        from_native,
        to_native,
    )

    payloads = [b"", b"x" * 7, b"y" * 129, b"z" * 256]
    mh, ml, lens = pack_payloads(payloads, nblocks=2)
    mh_n, ml_n, len_n, B = to_native(
        jnp.asarray(mh), jnp.asarray(ml), jnp.asarray(lens)
    )
    hh, hl = blake2b_native(mh_n, ml_n, len_n, interpret=True,
                            msg_loads=True, vmem_state=True,
                            g_interleave=True)
    assert digests_to_bytes(*from_native(hh, hl, B)) == [
        hashlib.blake2b(p, digest_size=32).digest() for p in payloads
    ]
