// Native runtime for dat_replication_protocol_tpu: the host-side hot loops.
//
// The reference's hot receive path is a byte-at-a-time varint scan and
// per-frame dispatch in JS (reference: decode.js:144-169, 251-262).  The
// TPU-native framework needs the same parsing at change-log-replay scale
// (BASELINE.json config 2: 1M-row replay) where per-record Python costs
// ~1us each; this translation unit provides the two tight loops behind a
// plain C ABI (loaded via ctypes — no pybind11 in the image):
//
//   dat_split_frames    multibuffer framing: varint(len+1) | id | payload
//   dat_decode_changes  proto2 `Change` records -> columnar arrays
//                       (zero-copy: strings/bytes become (offset, len)
//                       views into the log buffer — the layout the device
//                       feed packs from directly)
//
// Build: g++ -O3 -shared -fPIC (runtime/native.py does this on demand and
// caches the .so; every entry point has a pure-Python fallback).

#include <cstdint>
#include <cstddef>

namespace {

// Decode one unsigned LEB128 varint at buf[i..len).  Returns the number of
// bytes consumed (0 = truncated, -1 = overlong/>10 bytes).
inline int read_uvarint(const uint8_t* buf, int64_t i, int64_t len,
                        uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  for (int k = 0; k < 10; ++k) {
    if (i + k >= len) return 0;
    uint8_t b = buf[i + k];
    // 10th byte may only contribute bit 63: anything else encodes a
    // value >= 2^64 (overlong — matches the Python decoder's rejection).
    if (k == 9 && (b & 0x7F) > 1) return -1;
    v |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return k + 1;
    }
    shift += 7;
  }
  return -1;
}

}  // namespace

extern "C" {

// Error codes shared by both entry points.
enum {
  DAT_ERR_TRUNCATED = -1,
  DAT_ERR_CAPACITY = -2,
  DAT_ERR_BAD_VARINT = -3,
  DAT_ERR_BAD_RECORD = -4,
};

// Split a multibuffer stream into frames.
//
// Returns the count of complete valid frames (<= cap) and fills, per
// frame:
//   starts[f]  byte offset of the payload (after the id byte)
//   lens[f]    payload length (framed length minus the id byte)
//   ids[f]     the 1-byte type id (unvalidated; policy lives above)
// `consumed` gets the offset one past the last complete frame (a partial
// trailing frame is not an error — streaming callers re-feed the tail).
// A malformed header (overlong varint / zero framed length) STOPS the
// scan at that frame: the valid prefix is still returned and `err` gets
// the error code (0 otherwise), so a streaming caller can deliver the
// prefix and surface the error at exactly the offending frame — the same
// observable order as the byte-at-a-time scanner.  Only a capacity
// overflow (caller bug) is a negative return.
int64_t dat_split_frames(const uint8_t* buf, int64_t len, int64_t* starts,
                         int64_t* lens, uint8_t* ids, int64_t cap,
                         int64_t* consumed, int64_t* err) {
  int64_t i = 0;
  int64_t n = 0;
  *consumed = 0;
  *err = 0;
  while (i < len) {
    uint64_t framed;
    int used = read_uvarint(buf, i, len, &framed);
    if (used == 0) break;  // partial header at tail
    if (used < 0) {
      *err = DAT_ERR_BAD_VARINT;
      break;
    }
    if (framed == 0) {  // must include the id byte
      *err = DAT_ERR_BAD_RECORD;
      break;
    }
    // Unsigned compare BEFORE any int64 cast: a hostile length >= 2^63
    // must not wrap negative and walk the cursor backwards.  Anything
    // larger than the bytes on hand is a partial tail (streaming callers
    // re-feed), matching the Python fallback's NeedMoreData behavior.
    uint64_t remaining = static_cast<uint64_t>(len - i) - used;
    if (framed > remaining) break;  // partial frame at tail
    int64_t payload = static_cast<int64_t>(framed) - 1;
    int64_t frame_end = i + used + 1 + payload;
    if (n >= cap) return DAT_ERR_CAPACITY;
    ids[n] = buf[i + used];
    starts[n] = i + used + 1;
    lens[n] = payload;
    ++n;
    i = frame_end;
    *consumed = i;
  }
  return n;
}

// Greedy min/max chunk-size pass over sorted candidate byte offsets (the
// sequential tail of content-defined chunking; ops/rabin.py documents the
// algorithm).  Writes chunk end-offsets (exclusive), always ending with
// `length`.  Returns the cut count, or DAT_ERR_CAPACITY.
int64_t dat_greedy_select(const int64_t* cands, int64_t n, int64_t length,
                          int64_t min_size, int64_t max_size, int64_t* out,
                          int64_t cap) {
  int64_t start = 0, i = 0, m = 0;
  while (length - start > max_size) {
    int64_t lo = start + min_size;
    int64_t hi = start + max_size;
    while (i < n && cands[i] < lo) ++i;
    int64_t cut;
    if (i < n && cands[i] <= hi) {
      cut = cands[i];
      ++i;
    } else {
      cut = hi;
    }
    if (m >= cap) return DAT_ERR_CAPACITY;
    out[m++] = cut;
    start = cut;
  }
  if (m >= cap) return DAT_ERR_CAPACITY;
  out[m++] = length;
  return m;
}

// Proto2 tags for the Change message (reference: messages/schema.proto:1-8).
enum {
  TAG_SUBSET = (1 << 3) | 2,
  TAG_KEY = (2 << 3) | 2,
  TAG_CHANGE = (3 << 3) | 0,
  TAG_FROM = (4 << 3) | 0,
  TAG_TO = (5 << 3) | 0,
  TAG_VALUE = (6 << 3) | 2,
};

// Decode n Change payloads into columnar arrays.
//
// Absent optional fields get len -1 (host maps to ''/b'').  Unknown fields
// are skipped per proto2.  Returns 0, or a negative error with err_index
// set to the offending record.
int64_t dat_decode_changes(const uint8_t* buf, const int64_t* starts,
                           const int64_t* lens, int64_t n, uint32_t* change,
                           uint32_t* from_v, uint32_t* to_v, int64_t* key_off,
                           int64_t* key_len, int64_t* sub_off,
                           int64_t* sub_len, int64_t* val_off,
                           int64_t* val_len, int64_t* err_index) {
  for (int64_t r = 0; r < n; ++r) {
    int64_t i = starts[r];
    const int64_t end = i + lens[r];
    bool has_key = false, has_change = false, has_from = false, has_to = false;
    sub_len[r] = -1;
    val_len[r] = -1;
    sub_off[r] = 0;
    val_off[r] = 0;
    while (i < end) {
      uint64_t tag;
      int used = read_uvarint(buf, i, end, &tag);
      if (used <= 0) goto bad;
      i += used;
      switch (tag & 7) {
        case 0: {  // varint
          uint64_t v;
          used = read_uvarint(buf, i, end, &v);
          if (used <= 0) goto bad;
          i += used;
          if (tag == TAG_CHANGE) {
            change[r] = static_cast<uint32_t>(v);
            has_change = true;
          } else if (tag == TAG_FROM) {
            from_v[r] = static_cast<uint32_t>(v);
            has_from = true;
          } else if (tag == TAG_TO) {
            to_v[r] = static_cast<uint32_t>(v);
            has_to = true;
          }
          break;
        }
        case 2: {  // length-delimited
          uint64_t ln;
          used = read_uvarint(buf, i, end, &ln);
          if (used <= 0) goto bad;
          i += used;
          // Unsigned compare before the cast: ln >= 2^63 would go
          // negative as int64 and slip past the bounds check below.
          if (ln > static_cast<uint64_t>(end - i)) goto bad;
          if (tag == TAG_SUBSET) {
            sub_off[r] = i;
            sub_len[r] = static_cast<int64_t>(ln);
          } else if (tag == TAG_KEY) {
            key_off[r] = i;
            key_len[r] = static_cast<int64_t>(ln);
            has_key = true;
          } else if (tag == TAG_VALUE) {
            val_off[r] = i;
            val_len[r] = static_cast<int64_t>(ln);
          }
          i += static_cast<int64_t>(ln);
          break;
        }
        case 5:  // fixed32 (unknown field)
          if (i + 4 > end) goto bad;
          i += 4;
          break;
        case 1:  // fixed64 (unknown field)
          if (i + 8 > end) goto bad;
          i += 8;
          break;
        default:
          goto bad;
      }
    }
    if (!has_key || !has_change || !has_from || !has_to) goto bad;
    continue;
  bad:
    *err_index = r;
    return DAT_ERR_BAD_RECORD;
  }
  return 0;
}

}  // extern "C"

namespace {

inline int uvarint_size(uint64_t v) {
  int n = 1;
  while (v >= 0x80) {
    v >>= 7;
    ++n;
  }
  return n;
}

inline int64_t write_uvarint(uint8_t* dst, int64_t i, uint64_t v) {
  while (v >= 0x80) {
    dst[i++] = static_cast<uint8_t>(v) | 0x80;
    v >>= 7;
  }
  dst[i++] = static_cast<uint8_t>(v);
  return i;
}

}  // namespace

extern "C" {

// Bulk-encode n Change records (columnar, offsets into `src`) as framed
// wire bytes: varint(len+1) | 0x01 | proto payload, fields in ascending
// field-number order matching the Python encoder (wire/change_codec.py).
// sub_len/val_len -1 = absent optional.  Returns bytes written into
// `dst` (capacity `cap`), or DAT_ERR_CAPACITY.
int64_t dat_encode_changes(const uint8_t* src, int64_t n,
                           const uint32_t* change, const uint32_t* from_v,
                           const uint32_t* to_v, const int64_t* key_off,
                           const int64_t* key_len, const int64_t* sub_off,
                           const int64_t* sub_len, const int64_t* val_off,
                           const int64_t* val_len, uint8_t* dst,
                           int64_t cap) {
  int64_t w = 0;
  for (int64_t r = 0; r < n; ++r) {
    // payload size
    int64_t psize = 0;
    if (sub_len[r] >= 0)
      psize += 1 + uvarint_size(sub_len[r]) + sub_len[r];
    psize += 1 + uvarint_size(key_len[r]) + key_len[r];
    psize += 1 + uvarint_size(change[r]);
    psize += 1 + uvarint_size(from_v[r]);
    psize += 1 + uvarint_size(to_v[r]);
    if (val_len[r] >= 0)
      psize += 1 + uvarint_size(val_len[r]) + val_len[r];
    int64_t need = uvarint_size(psize + 1) + 1 + psize;
    if (w + need > cap) return DAT_ERR_CAPACITY;
    w = write_uvarint(dst, w, psize + 1);
    dst[w++] = 1;  // TYPE_CHANGE
    if (sub_len[r] >= 0) {
      dst[w++] = TAG_SUBSET;
      w = write_uvarint(dst, w, sub_len[r]);
      for (int64_t k = 0; k < sub_len[r]; ++k)
        dst[w + k] = src[sub_off[r] + k];
      w += sub_len[r];
    }
    dst[w++] = TAG_KEY;
    w = write_uvarint(dst, w, key_len[r]);
    for (int64_t k = 0; k < key_len[r]; ++k) dst[w + k] = src[key_off[r] + k];
    w += key_len[r];
    dst[w++] = TAG_CHANGE;
    w = write_uvarint(dst, w, change[r]);
    dst[w++] = TAG_FROM;
    w = write_uvarint(dst, w, from_v[r]);
    dst[w++] = TAG_TO;
    w = write_uvarint(dst, w, to_v[r]);
    if (val_len[r] >= 0) {
      dst[w++] = TAG_VALUE;
      w = write_uvarint(dst, w, val_len[r]);
      for (int64_t k = 0; k < val_len[r]; ++k)
        dst[w + k] = src[val_off[r] + k];
      w += val_len[r];
    }
  }
  return w;
}

}  // extern "C"
