"""Native C sources (compiled on demand by runtime.native)."""
