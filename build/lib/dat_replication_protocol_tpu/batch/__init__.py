"""Batching feed layer: ragged host data -> fixed-shape device batches."""

from .feed import bucketed_extents, hash_extents, leaves_from_columns, pack_ragged

__all__ = [
    "bucketed_extents",
    "hash_extents",
    "leaves_from_columns",
    "pack_ragged",
]
