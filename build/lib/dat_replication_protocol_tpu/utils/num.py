"""Small numeric helpers shared across layers."""

from __future__ import annotations


def next_pow2(n: int) -> int:
    """Smallest power of two >= max(n, 1).

    THE padding policy: batch axes, block counts, and mesh shards all
    round up with this one function — Merkle-root comparability between
    replicas depends on both sides padding identically, so the policy
    must have exactly one implementation.
    """
    p = 1
    while p < n:
        p <<= 1
    return p
