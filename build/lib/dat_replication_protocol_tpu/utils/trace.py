"""Profiling spans around host->device dispatch boundaries.

The reference has no tracing at all — only passive byte/frame counters
(reference: encode.js:51-53, decode.js:68-70).  At device scale that is
not enough: round 2 shipped a ~2000x CDC regression that a single trace
would have localized in minutes (the cost was H2D staging, not the
kernel).  SURVEY.md §5 therefore promises `jax.profiler` spans around
every dispatch; this module is that hook.

* :func:`span` — named annotation context.  Wrap host-side phases
  (packing, dispatch, collect) so they show up on the TraceViewer
  timeline next to the device ops.  Uses
  ``jax.profiler.TraceAnnotation``; ~ns overhead when no trace is
  active, so call sites leave it on unconditionally.
* :func:`trace_to` — whole-program capture into a profile directory
  (``bench.py --trace=DIR`` uses it; open with TensorBoard or Perfetto).

JAX is imported lazily: the session layer must stay importable (and
fast) in processes that never touch a device.
"""

from __future__ import annotations

import contextlib


class _NullSpan:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def span(name: str):
    """Named profiler annotation; inert if jax is unavailable."""
    try:
        from jax.profiler import TraceAnnotation
    except Exception:
        return _NullSpan()
    return TraceAnnotation(name)


@contextlib.contextmanager
def trace_to(log_dir: str | None):
    """Capture a jax profiler trace into ``log_dir`` (no-op if None)."""
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
