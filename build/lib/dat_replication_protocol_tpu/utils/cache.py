"""Persistent XLA compile-cache setup (one owner for all entry points).

The scanned-BLAKE2b / tree programs take minutes to compile cold on the
CPU backend and tens of seconds on TPU; a persistent cache turns reruns
(tests, bench, examples, driver re-runs) into cache hits.  Scope rules:

* keyed by platform + processor + jax version: AOT artifacts from a
  host with different CPU features can SIGILL when loaded;
* per-user path under the system temp dir: a predictable world-shared
  path would let another local user pre-seed attacker-controlled
  compiled artifacts (deserialized XLA programs execute).
"""

from __future__ import annotations

import hashlib
import os
import platform
import tempfile


def enable_compile_cache(tag: str, env_var: str | None = None) -> None:
    """Point jax at a persistent, scoped compile-cache directory.

    One shared directory serves every entry point (XLA keys entries per
    program, so tests warming the cache speeds up bench and vice versa);
    ``tag`` only labels the fallback log line.  ``env_var`` optionally
    names an environment variable that overrides the path.  Never
    raises: the cache is an optimization — but a disabled cache IS
    logged, because silently losing it costs minutes per cold compile.
    """
    try:
        import jax

        override = os.environ.get(env_var) if env_var else None
        if override:
            path = override
        else:
            scope = hashlib.blake2b(
                f"{platform.platform()}-{platform.processor()}-"
                f"{jax.__version__}".encode(),
                digest_size=6,
            ).hexdigest()
            user = f"u{os.getuid()}" if hasattr(os, "getuid") else "u0"
            path = os.path.join(
                tempfile.gettempdir(),
                f"dat_jax_cache-{user}-{scope}",
            )
        # create 0700 and verify ownership: a predictable path that
        # accepted a pre-existing foreign directory would let another
        # local user feed us attacker-controlled compiled artifacts.
        # lstat + symlink rejection: st_uid of the *target* passes the
        # ownership test when an attacker plants a symlink to a dir the
        # victim owns, redirecting cache reads/writes wherever they chose.
        # The hardening applies only to the *derived* (predictable)
        # default path — an operator-chosen override is trusted as given
        # (shared group caches and symlinked scratch disks are legitimate
        # there, and the planted-path attack needs a predictable target)
        os.makedirs(path, mode=0o700, exist_ok=True)
        if not override:
            st = os.lstat(path)
            import stat as stat_mod

            if stat_mod.S_ISLNK(st.st_mode):
                raise PermissionError(f"{path} is a symlink")
            if hasattr(os, "getuid"):  # POSIX-only: Windows fakes 0o777
                if st.st_uid != os.getuid():
                    raise PermissionError(f"{path} owned by another user")
                if st.st_mode & 0o022:
                    raise PermissionError(f"{path} group/world-writable")
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception as e:
        import sys

        print(f"{tag}: compile cache disabled ({e}); cold compiles ahead",
              file=sys.stderr)
