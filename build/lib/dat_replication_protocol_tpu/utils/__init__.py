from .trace import span, trace_to  # noqa: F401
