"""L2 session layer: Encoder / Decoder objects and the loopback pipe."""

from .decoder import BlobReader, Decoder, DecoderDestroyedError
from .encoder import (
    BlobLengthError,
    BlobWriter,
    Encoder,
    EncoderDestroyedError,
)
from .pipe import Pipe, pipe

__all__ = [
    "BlobReader",
    "Decoder",
    "DecoderDestroyedError",
    "BlobLengthError",
    "BlobWriter",
    "Encoder",
    "EncoderDestroyedError",
    "Pipe",
    "pipe",
]
