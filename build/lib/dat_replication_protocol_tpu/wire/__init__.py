"""L1/L3 wire layer: varint, framing, and the Change protobuf codec."""

from .change_codec import Change, decode_change, encode_change
from .framing import (
    KNOWN_TYPES,
    MAX_HEADER_LEN,
    TYPE_BLOB,
    TYPE_CHANGE,
    TYPE_HEADER,
    ProtocolError,
    frame,
    frame_header,
)
from .varint import NeedMoreData, decode_uvarint, encode_uvarint, uvarint_length

__all__ = [
    "Change",
    "decode_change",
    "encode_change",
    "KNOWN_TYPES",
    "MAX_HEADER_LEN",
    "TYPE_BLOB",
    "TYPE_CHANGE",
    "TYPE_HEADER",
    "ProtocolError",
    "frame",
    "frame_header",
    "NeedMoreData",
    "decode_uvarint",
    "encode_uvarint",
    "uvarint_length",
]
