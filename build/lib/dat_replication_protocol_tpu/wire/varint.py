"""Unsigned LEB128 varints — the length prefix of every wire frame.

Capability parity: the reference uses the `varint` npm package for both the
frame-length prefix (reference: encode.js:132, decode.js:255) and inside the
protobuf codec. This is a fresh implementation of the same encoding.

A varint stores an unsigned integer 7 bits at a time, least-significant group
first; the high bit of each byte is a continuation flag. Values up to 2^64-1
fit in 10 bytes; the framing layer caps headers at MAX_VARINT_LEN.
"""

from __future__ import annotations

MAX_VARINT_LEN = 10  # enough for any uint64


def encode_uvarint(value: int) -> bytes:
    """Encode a non-negative integer as an unsigned LEB128 varint."""
    if value < 0:
        raise ValueError(f"varint cannot encode negative value {value}")
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_uvarint(buf, offset: int = 0) -> tuple[int, int]:
    """Decode a varint from ``buf`` starting at ``offset``.

    Returns ``(value, bytes_consumed)``. Raises ``ValueError`` on a varint
    longer than MAX_VARINT_LEN and ``IndexError``-style truncation via
    ``NeedMoreData`` if the buffer ends mid-varint.
    """
    value = 0
    shift = 0
    i = offset
    n = len(buf)
    while True:
        if i >= n:
            raise NeedMoreData("truncated varint")
        b = buf[i]
        i += 1
        value |= (b & 0x7F) << shift
        if not (b & 0x80):
            if value >= 1 << 64:
                raise ValueError("varint exceeds 64 bits")
            return value, i - offset
        shift += 7
        if i - offset >= MAX_VARINT_LEN:
            raise ValueError("varint too long (corrupt frame header)")


def uvarint_length(value: int) -> int:
    """Number of bytes :func:`encode_uvarint` would produce."""
    n = 1
    value >>= 7
    while value:
        n += 1
        value >>= 7
    return n


class NeedMoreData(Exception):
    """Raised when a decode needs more bytes than the buffer holds."""
