"""Device ops: batched hashing, chunking, and tree kernels (JAX/XLA/Pallas)."""

from .blake2b import blake2b_batch, blake2b_packed, digests_to_bytes, pack_payloads
from .merkle import build_tree, diff_leaves, diff_root_guided, merkle_level
from .rabin import chunk_stream, gear_candidates_tiled
from .u64 import add64, mul64, ror64, shl64, shr64, to_pair, xor64

__all__ = [
    "blake2b_batch",
    "blake2b_packed",
    "build_tree",
    "chunk_stream",
    "diff_leaves",
    "diff_root_guided",
    "gear_candidates_tiled",
    "merkle_level",
    "digests_to_bytes",
    "pack_payloads",
    "add64",
    "mul64",
    "ror64",
    "shl64",
    "shr64",
    "to_pair",
    "xor64",
]
