"""64-bit word arithmetic as (hi, lo) uint32 lane pairs.

TPUs have no native 64-bit integer lanes; every 64-bit quantity in the device
kernels (BLAKE2b state words, gear-hash accumulators, Merkle node words) is
represented as a pair of uint32 arrays ``(hi, lo)``.  All helpers are shape-
polymorphic elementwise ops, so they vectorize over arbitrary batch dims and
fuse under jit.  This is the "lane-pair emulation" SURVEY.md §7 names as a
hard part of byte-exact BLAKE2b on TPU.

The reference has no analogue (pure JS, no hashing); these ops exist to serve
the framework's device data plane (BASELINE.json north star).
"""

from __future__ import annotations

import jax.numpy as jnp

U32 = jnp.uint32
MASK32 = jnp.uint32(0xFFFFFFFF)


def add64(ah, al, bh, bl):
    """(ah,al) + (bh,bl) mod 2**64. uint32 addition wraps, carry = lo < al."""
    lo = al + bl
    carry = (lo < al).astype(U32)
    hi = ah + bh + carry
    return hi, lo


def add64_3(ah, al, bh, bl, ch, cl):
    """Three-way 64-bit add (the BLAKE2b G step `a = a + b + x`)."""
    hi, lo = add64(ah, al, bh, bl)
    return add64(hi, lo, ch, cl)


def xor64(ah, al, bh, bl):
    return ah ^ bh, al ^ bl


def ror64(hi, lo, r: int):
    """Rotate right by a static amount r in [1, 63].

    r == 32 is a pure hi/lo swap; r < 32 and r > 32 are the two shifted
    cross-lane blends.  r is a Python int so each case compiles to a fixed
    pair of shifts — no data-dependent control flow under jit.
    """
    r = int(r) % 64
    if r == 0:
        return hi, lo
    if r == 32:
        return lo, hi
    if r < 32:
        s, t = U32(r), U32(32 - r)
        new_lo = (lo >> s) | (hi << t)
        new_hi = (hi >> s) | (lo << t)
        return new_hi, new_lo
    # r > 32: rotate by 32 (swap) then by r - 32
    return ror64(lo, hi, r - 32)


def shl64(hi, lo, s: int):
    """Logical shift left by static s in [0, 63]."""
    s = int(s)
    if s == 0:
        return hi, lo
    if s >= 32:
        return (lo << U32(s - 32)) if s > 32 else lo, jnp.zeros_like(lo)
    return (hi << U32(s)) | (lo >> U32(32 - s)), lo << U32(s)


def shr64(hi, lo, s: int):
    """Logical shift right by static s in [0, 63]."""
    s = int(s)
    if s == 0:
        return hi, lo
    if s >= 32:
        return jnp.zeros_like(hi), (hi >> U32(s - 32)) if s > 32 else hi
    return hi >> U32(s), (lo >> U32(s)) | (hi << U32(32 - s))


def mul64(ah, al, bh, bl):
    """(a * b) mod 2**64 via 16-bit limb products (no 64-bit multiply lanes).

    Splits each 32-bit lane into 16-bit halves so every partial product fits
    in uint32 without losing carries; used by the gear/Rabin rolling-hash
    scan combiner.
    """
    a0, a1 = al & U32(0xFFFF), al >> U32(16)
    b0, b1 = bl & U32(0xFFFF), bl >> U32(16)

    # low 32x32 -> 64 product of al * bl
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1

    mid = p01 + p10  # may wrap: track its carry into the high word
    mid_carry = (mid < p01).astype(U32) << U32(16)

    lo = p00 + (mid << U32(16))
    lo_carry = (lo < p00).astype(U32)
    hi = p11 + (mid >> U32(16)) + mid_carry + lo_carry

    # cross terms only affect the high 32 bits (mod 2**64)
    hi = hi + al * bh + ah * bl
    return hi, lo


def to_pair(x: int):
    """Split a Python int into (hi, lo) uint32 scalars."""
    x = int(x) & 0xFFFFFFFFFFFFFFFF
    return U32(x >> 32), U32(x & 0xFFFFFFFF)
