#!/bin/bash
# Probe the tunnel every ~5 min (subprocess probe, 100 s cap — a wedged
# tunnel hangs rather than erroring); whenever a probe EXECUTES a device
# op, fire _when_tpu_returns.sh.  Round-5 change: the loop RE-ARMS
# after firing — rounds 3 and 4 both saw windows die mid-agenda, and a
# one-shot loop wastes any later window.  The agenda's legs are
# individually resumable (.leg_*_done markers), so a re-fire only runs
# what is still missing; the loop exits once every leg is done.
cd "$(dirname "$0")"
OUT=artifacts/r05_watch
while true; do
  if [ -f "$OUT/.leg_quick_done" ] && [ -f "$OUT/.leg_full_done" ] \
     && [ -f "$OUT/.leg_observe_done" ] && [ -f "$OUT/.leg_reconcile_done" ]; then
    echo "$(date -u) all agenda legs captured; watch retiring" >> /tmp/tpu_watch.log
    exit 0
  fi
  if timeout 100 python -c "
import jax, numpy as np, jax.numpy as jnp
x = np.asarray(jnp.arange(8) * 2)
assert x[3] == 6
" >/dev/null 2>&1; then
    echo "$(date -u) tunnel answered; firing capture" >> /tmp/tpu_watch.log
    bash _when_tpu_returns.sh >> /tmp/tpu_watch.log 2>&1
    # brief pause, then keep probing: if the window died mid-agenda the
    # next healthy probe re-fires the remaining legs
    sleep 60
    continue
  fi
  echo "$(date -u) probe failed" >> /tmp/tpu_watch.log
  sleep 300
done
