#!/bin/bash
# Probe the tunnel every ~5 min (subprocess probe, 100 s cap — a wedged
# tunnel hangs rather than erroring); the moment a probe EXECUTES a
# device op, fire _when_tpu_returns.sh once and exit.  Round-3/4 wedge
# signature: platform initializes, first compute hangs forever.
cd "$(dirname "$0")"
while true; do
  if timeout 100 python -c "
import jax, numpy as np, jax.numpy as jnp
x = np.asarray(jnp.arange(8) * 2)
assert x[3] == 6
" >/dev/null 2>&1; then
    echo "$(date -u) tunnel answered; firing capture" >> /tmp/tpu_watch.log
    bash _when_tpu_returns.sh >> /tmp/tpu_watch.log 2>&1
    exit 0
  fi
  echo "$(date -u) probe failed" >> /tmp/tpu_watch.log
  sleep 300
done
